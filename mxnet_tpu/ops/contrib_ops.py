"""Contrib ops: FFT, detection (MultiBox*/NMS/IOU), ROI pooling/align,
spatial transformer, correlation, and misc contrib utilities.

Parity targets: `src/operator/contrib/fft/`, `multibox_prior.cc`,
`multibox_target.cc`, `multibox_detection.cc`, `bounding_box.cc`,
`src/operator/roi_pooling.cc`, `contrib/roi_align.cc`,
`spatial_transformer.cc`, `grid_generator.cc`, `bilinear_sampler.cc`,
`contrib/correlation.cc`, `contrib/bilinear_resize.cc`,
`contrib/boolean_mask.cc`, `contrib/index_copy.cc`,
`contrib/multi_all_finite.cc`, `im2col.h`.

TPU-native notes: everything is static-shape. NMS keeps the input shape
and writes -1 into suppressed slots (exactly the reference's contract,
which happens to be the TPU-friendly formulation — no dynamic output).
ROI ops vmap over boxes with gather-based sampling; bilinear sampling is
a 4-corner gather, fully fused by XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


# ------------------------------------------------------------------- fft ----

@register("_contrib_fft")
def _contrib_fft(data, compute_size=128):
    """FFT along the last axis; complex output interleaved as
    [..., 2*d] (re, im, re, im, ...) — parity: contrib/fft/fft-inl.h."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register("_contrib_ifft")
def _contrib_ifft(data, compute_size=128):
    """Inverse of `_contrib_fft`'s interleaved layout; returns the real
    part scaled like the reference (no 1/N — cuFFT semantics)."""
    d = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (d, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    return (jnp.fft.ifft(comp, axis=-1).real * d).astype(jnp.float32)


# ------------------------------------------------------------- detection ----

@register("MultiBoxPrior", aliases=("_contrib_MultiBoxPrior",))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor box generation (parity: multibox_prior.cc). Output
    (1, H*W*(num_sizes+num_ratios-1), 4) corner-format boxes."""
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1)  # H,W,2
    # anchor shapes: (size_i, ratio_0) for all sizes, (size_0, ratio_j>0)
    whs = []
    for s in sizes:
        r = ratios[0]
        whs.append((s * jnp.sqrt(r), s / jnp.sqrt(r)))
    for r in ratios[1:]:
        s = sizes[0]
        whs.append((s * jnp.sqrt(r), s / jnp.sqrt(r)))
    whs = jnp.asarray(whs, jnp.float32)  # (A, 2) = (w, h)
    a = whs.shape[0]
    cyx_b = jnp.broadcast_to(cyx[:, :, None, :], (h, w, a, 2))
    half_w = whs[None, None, :, 0] / 2
    half_h = whs[None, None, :, 1] / 2
    xmin = cyx_b[..., 1] - half_w
    ymin = cyx_b[..., 0] - half_h
    xmax = cyx_b[..., 1] + half_w
    ymax = cyx_b[..., 0] + half_h
    out = jnp.stack([xmin, ymin, xmax, ymax], axis=-1).reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _center_to_corner(b):
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _corner_to_center(b):
    x1, y1, x2, y2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                     axis=-1)


def _iou_corner(lhs, rhs):
    """IOU between (..., N, 4) and (..., M, 4) corner boxes -> (..., N, M)."""
    lx1, ly1, lx2, ly2 = [lhs[..., :, None, i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., None, :, i] for i in range(4)]
    iw = jnp.maximum(jnp.minimum(lx2, rx2) - jnp.maximum(lx1, rx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ly2, ry2) - jnp.maximum(ly1, ry1), 0.0)
    inter = iw * ih
    area_l = jnp.maximum(lx2 - lx1, 0.0) * jnp.maximum(ly2 - ly1, 0.0)
    area_r = jnp.maximum(rx2 - rx1, 0.0) * jnp.maximum(ry2 - ry1, 0.0)
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_box_iou")
def _contrib_box_iou(lhs, rhs, format="corner"):
    """parity: bounding_box.cc box_iou."""
    if format == "center":
        lhs, rhs = _center_to_corner(lhs), _center_to_corner(rhs)
    return _iou_corner(lhs, rhs)


def _nms_core(boxes, scores, ids, valid, overlap_thresh, topk):
    """Greedy NMS over one batch element; returns keep mask (bool [N])."""
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    boxes_o = boxes[order]
    valid_o = valid[order]
    iou = _iou_corner(boxes_o, boxes_o)
    same_class = ids[order][:, None] == ids[order][None, :]

    def body(i, keep):
        # suppress j>i overlapping with kept i of the same class
        sup = (iou[i] > overlap_thresh) & same_class[i] & \
            (jnp.arange(n) > i) & keep[i] & valid_o[i]
        return keep & ~sup

    keep = jax.lax.fori_loop(0, n if topk < 0 else min(topk, n), body,
                             valid_o)
    # un-sort
    inv = jnp.argsort(order)
    return keep[inv]


@register("box_nms", aliases=("_contrib_box_nms",), num_outputs=1)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, force_suppress=False,
             in_format="corner", out_format="corner", background_id=-1):
    """NMS keeping input shape, suppressed entries set to -1
    (parity: bounding_box.cc BoxNMS)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])  # (B, N, K)
    boxes = flat[..., coord_start:coord_start + 4]
    if in_format == "center":
        boxes = _center_to_corner(boxes)
    if out_format != in_format:
        out_boxes = boxes if out_format == "corner" \
            else _corner_to_center(boxes)
        flat = flat.at[..., coord_start:coord_start + 4].set(out_boxes)
    scores = flat[..., score_index]
    if id_index >= 0 and not force_suppress:
        ids = flat[..., id_index]
    else:
        ids = jnp.zeros_like(scores)
    valid = scores > valid_thresh
    if id_index >= 0 and background_id >= 0:
        valid = valid & (flat[..., id_index] != background_id)
    keep = jax.vmap(
        lambda b, s, i, v: _nms_core(b, s, i, v, overlap_thresh, topk)
    )(boxes, scores, ids, valid)
    out = jnp.where(keep[..., None], flat, -jnp.ones_like(flat))
    return out.reshape(shape)


@register("MultiBoxTarget", aliases=("_contrib_MultiBoxTarget",),
          num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor matching + target encoding (parity: multibox_target.cc).
    anchor: (1, N, 4); label: (B, M, 5) [cls, x1, y1, x2, y2] (-1 pad);
    cls_pred: (B, num_cls+1, N). Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N))."""
    anchors = anchor[0]  # (N, 4)
    n = anchors.shape[0]

    def per_sample(lab, cls_pred_s):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anchors, gt_boxes)  # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        # force-match each VALID gt to its best anchor (`.add` not `.set`:
        # padded gt rows all argmax to anchor 0 and a duplicate-index .set
        # could erase a valid gt's forced match)
        best_anchor = jnp.argmax(iou, axis=0)  # (M,)
        forced = jnp.zeros((n,), jnp.int32) \
            .at[best_anchor].add(gt_valid.astype(jnp.int32)) > 0
        pos = (best_iou >= overlap_threshold) | forced
        matched_gt = gt_boxes[best_gt]
        matched_cls = lab[best_gt, 0]
        # encode: center offsets normalized by variances
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = matched_gt[:, 2] - matched_gt[:, 0]
        gh = matched_gt[:, 3] - matched_gt[:, 1]
        gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
        gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
        eps = 1e-8
        tx = (gcx - acx) / jnp.maximum(aw, eps) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, eps) / variances[1]
        tw = jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)) \
            / variances[2]
        th = jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)) \
            / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(pos[:, None],
                          jnp.ones((n, 4), anchors.dtype), 0.0).reshape(-1)
        cls_t = jnp.where(pos, matched_cls + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining (multibox_target.cc): rank unmatched
            # anchors by max non-background confidence, keep the top
            # ratio*num_pos as background samples, ignore the rest
            neg_conf = jnp.max(cls_pred_s[1:], axis=0)  # (N,)
            eligible = (~pos) & (best_iou < negative_mining_thresh)
            num_pos = jnp.sum(pos)
            num_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples)
            score = jnp.where(eligible, neg_conf, -jnp.inf)
            order = jnp.argsort(-score)
            rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n))
            keep_neg = eligible & (rank < num_neg)
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(keep_neg, 0.0, ignore_label))
        return loc_t, loc_m, cls_t

    loc_target, loc_mask, cls_target = jax.vmap(per_sample)(
        label, cls_pred)
    return loc_target, loc_mask, cls_target


@register("MultiBoxDetection", aliases=("_contrib_MultiBoxDetection",))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0,
                        nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions to detections + NMS (parity:
    multibox_detection.cc). cls_prob: (B, C, N); loc_pred: (B, N*4);
    anchor: (1, N, 4). Output (B, N, 6) [id, score, x1, y1, x2, y2]."""
    b, c, n = cls_prob.shape
    anchors = anchor[0]
    loc = loc_pred.reshape(b, n, 4)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = loc[..., 0] * variances[0] * aw + acx
    cy = loc[..., 1] * variances[1] * ah + acy
    w = jnp.exp(loc[..., 2] * variances[2]) * aw / 2
    h = jnp.exp(loc[..., 3] * variances[3]) * ah / 2
    boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    # best non-background class per anchor
    fg = jnp.concatenate([cls_prob[:, :background_id],
                          cls_prob[:, background_id + 1:]], axis=1) \
        if 0 <= background_id < c else cls_prob
    best = jnp.argmax(fg, axis=1).astype(jnp.float32)  # (B, N)
    score = jnp.max(fg, axis=1)
    keep_score = score > threshold
    det = jnp.concatenate([
        jnp.where(keep_score, best, -1.0)[..., None],
        jnp.where(keep_score, score, 0.0)[..., None], boxes], axis=-1)
    return _box_nms.fn(det, overlap_thresh=nms_threshold,
                       valid_thresh=threshold, topk=nms_topk,
                       coord_start=2, score_index=1, id_index=0,
                       force_suppress=force_suppress)


# ------------------------------------------------------------------ rois ----

def _bilinear_gather(img, ys, xs):
    """Bilinear sample img (C, H, W) at float coords (ys, xs) of any
    shape -> (C, *coords.shape). Out-of-range clamps (edge padding)."""
    h, w = img.shape[-2], img.shape[-1]
    y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = jnp.clip(ys - y0, 0.0, 1.0)
    wx = jnp.clip(xs - x0, 0.0, 1.0)
    y0i, y1i, x0i, x1i = (y0.astype(jnp.int32), y1.astype(jnp.int32),
                          x0.astype(jnp.int32), x1.astype(jnp.int32))
    v00 = img[:, y0i, x0i]
    v01 = img[:, y0i, x1i]
    v10 = img[:, y1i, x0i]
    v11 = img[:, y1i, x1i]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


@register("ROIPooling")
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max pooling over regions (parity: roi_pooling.cc). rois: (R, 5)
    [batch_idx, x1, y1, x2, y2] in image coords."""
    ph, pw = pooled_size
    h, w = data.shape[2], data.shape[3]

    def one_roi(roi):
        img = data[roi[0].astype(jnp.int32)]  # (C, H, W)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        # sample a fixed 2x2 grid per bin, take max (static-shape stand-in
        # for the reference's variable-size bin max)
        sy = jnp.arange(ph)[:, None, None, None]
        sx = jnp.arange(pw)[None, :, None, None]
        oy = (jnp.arange(2)[None, None, :, None] + 0.5) / 2
        ox = (jnp.arange(2)[None, None, None, :] + 0.5) / 2
        ys = jnp.clip(y1 + (sy + oy) * bin_h, 0, h - 1)
        xs = jnp.clip(x1 + (sx + ox) * bin_w, 0, w - 1)
        ys = jnp.broadcast_to(ys, (ph, pw, 2, 2))
        xs = jnp.broadcast_to(xs, (ph, pw, 2, 2))
        vals = img[:, ys.astype(jnp.int32), xs.astype(jnp.int32)]
        return vals.max(axis=(-2, -1))

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign")
def _roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sample_ratio=2, position_sensitive=False, aligned=False):
    """Bilinear average pooling over regions (parity: roi_align.cc)."""
    ph, pw = pooled_size
    s = max(int(sample_ratio), 1)

    def one_roi(roi):
        img = data[roi[0].astype(jnp.int32)]
        off = 0.5 if aligned else 0.0
        x1 = roi[1] * spatial_scale - off
        y1 = roi[2] * spatial_scale - off
        x2 = roi[3] * spatial_scale - off
        y2 = roi[4] * spatial_scale - off
        rh = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
        rw = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        sy = jnp.arange(ph)[:, None, None, None]
        sx = jnp.arange(pw)[None, :, None, None]
        oy = (jnp.arange(s)[None, None, :, None] + 0.5) / s
        ox = (jnp.arange(s)[None, None, None, :] + 0.5) / s
        ys = jnp.broadcast_to(y1 + (sy + oy) * bin_h, (ph, pw, s, s))
        xs = jnp.broadcast_to(x1 + (sx + ox) * bin_w, (ph, pw, s, s))
        return _bilinear_gather(img, ys, xs).mean(axis=(-2, -1))

    return jax.vmap(one_roi)(rois)


# -------------------------------------------------- spatial transformer ----

@register("GridGenerator")
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Affine/warp sampling grid (parity: grid_generator.cc). Output
    (B, 2, H, W) with (x, y) in [-1, 1]."""
    if transform_type == "affine":
        b = data.shape[0]
        h, w = target_shape
        theta = data.reshape(b, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3,HW)
        out = jnp.einsum("bij,jk->bik", theta, coords)  # (B, 2, HW)
        return out.reshape(b, 2, h, w)
    # warp: data is (B, 2, H, W) flow field added to the identity grid
    b, _, h, w = data.shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy])[None]
    norm = jnp.asarray([(w - 1) / 2.0, (h - 1) / 2.0],
                       data.dtype).reshape(1, 2, 1, 1)
    return base + data / norm


@register("BilinearSampler")
def _bilinear_sampler(data, grid, cudnn_off=False):
    """Sample data (B,C,H,W) at grid (B,2,Ho,Wo) in [-1,1] (parity:
    bilinear_sampler.cc). Out-of-range -> 0 (border zero-padding)."""
    h, w = data.shape[2], data.shape[3]
    xs = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    ys = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    inside = ((xs >= -1) & (xs <= w) & (ys >= -1) & (ys <= h))

    out = jax.vmap(_bilinear_gather)(data, ys, xs)
    return out * inside[:, None].astype(data.dtype)


@register("SpatialTransformer")
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=False):
    """parity: spatial_transformer.cc — affine grid + bilinear sample."""
    grid = _grid_generator.fn(loc, transform_type="affine",
                              target_shape=tuple(target_shape))
    return _bilinear_sampler.fn(data, grid)


# ------------------------------------------------------------------ misc ----

@register("_contrib_BilinearResize2D")
def _bilinear_resize2d(data, height=1, width=1, scale_height=None,
                       scale_width=None, mode="size"):
    """parity: contrib/bilinear_resize.cc via jax.image.resize."""
    h = int(data.shape[2] * scale_height) if scale_height else height
    w = int(data.shape[3] * scale_width) if scale_width else width
    return jax.image.resize(data, data.shape[:2] + (h, w), method="linear")


@register("Correlation")
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """Cost-volume correlation (parity: contrib/correlation.cc,
    FlowNet-style). Simplified to kernel_size=1 semantics: output channel
    per displacement (d2 shifted), mean over channels."""
    b, c, h, w = data1.shape
    d = max_displacement
    p1 = jnp.pad(data2, ((0, 0), (0, 0), (d + pad_size, d + pad_size),
                         (d + pad_size, d + pad_size)))
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            shifted = jax.lax.dynamic_slice(
                p1, (0, 0, d + pad_size + dy, d + pad_size + dx),
                (b, c, h, w))
            if is_multiply:
                outs.append((data1 * shifted).mean(axis=1))
            else:
                outs.append(jnp.abs(data1 - shifted).mean(axis=1))
    return jnp.stack(outs, axis=1)


@register("_contrib_boolean_mask", eager=True, differentiable=False)
def _boolean_mask(data, index, axis=0):
    """parity: contrib/boolean_mask.cc (dynamic output -> eager)."""
    idx = jnp.nonzero(index)[0]
    return jnp.take(data, idx, axis=axis)


@register("_contrib_index_copy")
def _index_copy(old, index, new_tensor):
    """parity: contrib/index_copy.cc — copy rows of new_tensor into old."""
    return old.at[index.astype(jnp.int32)].set(new_tensor)


@register("_contrib_arange_like")
def _contrib_arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """parity: contrib/arange_like — arange shaped like `data` along
    `axis` (flat when None), each value emitted `repeat` times."""
    n = data.shape[axis] if axis is not None else data.size
    # each value emitted `repeat` times (parity: arange_like contract)
    return start + step * (jnp.arange(n) // max(int(repeat), 1)) \
        .astype(jnp.float32)


@register("multi_all_finite")
def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """1 when every element of every input is finite (parity:
    contrib/multi_all_finite.cc — the AMP overflow check)."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = ok & jnp.isfinite(a).all()
    return ok.astype(jnp.float32).reshape((1,))


@register("_contrib_count_sketch")
def _count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    """Count sketch projection (parity: contrib/count_sketch.cc)."""
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(data * sign)


@register("im2col")
def _im2col(data, kernel=(), stride=(), dilate=(), pad=()):
    """parity: im2col.h — patch extraction for explicit GEMM conv."""
    n = len(kernel)
    stride = tuple(stride) if stride else (1,) * n
    dilate = tuple(dilate) if dilate else (1,) * n
    pad = tuple(pad) if pad else (0,) * n
    patches = jax.lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate)
    # (B, C*prod(kernel), *out_spatial) -> (B, C*prod(kernel), prod(out))
    return patches.reshape(patches.shape[0], patches.shape[1], -1)


@register("BlockGrad", aliases=("stop_gradient",))
def _block_grad(data):
    """parity: src/operator/tensor/elemwise_unary_op_basic.cc BlockGrad."""
    return jax.lax.stop_gradient(data)


# ------------------------------------------------- transformer matmuls -----
# parity: src/operator/contrib/transformer.cc — the interleaved-projection
# attention matmuls MXNet's transformer example uses. Layout: qkv is
# (seq, batch, 3*heads*head_dim) with q/k/v interleaved per head. On TPU
# these are einsums the MXU eats directly; no special kernel needed.

def _split_interleaved(qkv, heads, parts):
    seq, bsz, proj = qkv.shape
    head_dim = proj // (parts * heads)
    x = qkv.reshape(seq, bsz, heads, parts, head_dim)
    return [x[:, :, :, i, :] for i in range(parts)]  # each (s, b, h, d)


@register("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_selfatt_qk(queries_keys_values, heads=1):
    """parity: contrib/transformer.cc — scaled q@k^T attention scores
    from an interleaved qkv projection, flattened to (b*h, q, k)."""
    q, k, _ = _split_interleaved(queries_keys_values, heads, 3)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    att = jnp.einsum("qbhd,kbhd->bhqk", q * scale, k)
    b, h, s, _ = att.shape
    return att.reshape(b * h, s, s)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_selfatt_valatt(queries_keys_values, attention, heads=1):
    """parity: contrib/transformer.cc — attention-weighted values from
    the interleaved qkv projection, back to (seq, batch, h*d)."""
    _, _, v = _split_interleaved(queries_keys_values, heads, 3)
    s, b, h, d = v.shape
    att = attention.reshape(b, h, s, s)
    out = jnp.einsum("bhqk,kbhd->qbhd", att, v)
    return out.reshape(s, b, h * d)


@register("_contrib_interleaved_matmul_encdec_qk")
def _interleaved_encdec_qk(queries, keys_values, heads=1):
    """parity: contrib/transformer.cc — encoder-decoder q@k^T scores
    (separate queries, interleaved kv), flattened to (b*h, q, k)."""
    qs, b, proj = queries.shape
    d = proj // heads
    q = queries.reshape(qs, b, heads, d)
    k, _ = _split_interleaved(keys_values, heads, 2)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    att = jnp.einsum("qbhd,kbhd->bhqk", q * scale, k)
    ks = k.shape[0]
    return att.reshape(b * heads, qs, ks)


@register("_contrib_interleaved_matmul_encdec_valatt")
def _interleaved_encdec_valatt(keys_values, attention, heads=1):
    """parity: contrib/transformer.cc — attention-weighted values from
    the interleaved kv projection, back to (q_seq, batch, h*d)."""
    _, v = _split_interleaved(keys_values, heads, 2)
    ks, b, h, d = v.shape
    qs = attention.shape[1]
    att = attention.reshape(b, h, qs, ks)
    out = jnp.einsum("bhqk,kbhd->qbhd", att, v)
    return out.reshape(qs, b, h * d)


# ------------------------------------------------------------ box codec ----

@register("_contrib_box_encode", num_outputs=2)
def _box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
                stds=(0.1, 0.1, 0.2, 0.2)):
    """parity: contrib/bounding_box.cc BoxEncode — corner boxes ->
    regression targets for matched anchors (SSD/Faster-RCNN training)."""
    ax, ay, aw, ah = _corner_to_center(anchors)
    matched = jnp.take_along_axis(
        refs, jnp.maximum(matches, 0).astype(jnp.int32)[..., None], axis=1)
    gx, gy, gw, gh = _corner_to_center(matched)
    means = jnp.asarray(means, anchors.dtype)
    stds = jnp.asarray(stds, anchors.dtype)
    t = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                   jnp.log(gw / aw), jnp.log(gh / ah)], axis=-1)
    t = (t - means) / stds
    mask = (samples > 0.5)[..., None]
    return jnp.where(mask, t, jnp.zeros_like(t)), \
        jnp.broadcast_to(mask, t.shape).astype(t.dtype)


def _corner_to_center(boxes):
    xmin, ymin, xmax, ymax = [boxes[..., i] for i in range(4)]
    w = xmax - xmin
    h = ymax - ymin
    return xmin + w / 2, ymin + h / 2, w, h


@register("_contrib_box_decode")
def _box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
                clip=-1.0, format="corner"):
    """parity: contrib/bounding_box.cc BoxDecode — regression deltas back
    to corner boxes."""
    if format == "corner":
        ax, ay, aw, ah = _corner_to_center(anchors)
    else:
        ax, ay, aw, ah = [anchors[..., i] for i in range(4)]
    stds = jnp.asarray([std0, std1, std2, std3], data.dtype)
    d = data * stds
    cx = d[..., 0] * aw + ax
    cy = d[..., 1] * ah + ay
    dw, dh = d[..., 2], d[..., 3]
    if clip is not None and clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register("_contrib_bipartite_matching", num_outputs=2,
          differentiable=False)
def _bipartite_matching(data, is_ascend=False, threshold=0.0, topk=-1):
    """parity: contrib/bounding_box.cc BipartiteMatching — greedy one-to-one
    row/col matching by score (the SSD target matcher). lax.scan over the
    match rounds keeps it jittable."""
    b, rows, cols = data.shape
    n_rounds = min(rows, cols) if topk <= 0 else min(topk, rows, cols)
    big = jnp.asarray(float("inf"), data.dtype)
    score = -data if is_ascend else data
    passes = (data >= threshold) if not is_ascend else (data <= threshold)
    score = jnp.where(passes, score, -big)

    def one_round(state, _):
        s, row_out, col_out = state
        flat = s.reshape(b, -1)
        best = jnp.argmax(flat, axis=1)
        ri, ci = best // cols, best % cols
        valid = jnp.take_along_axis(flat, best[:, None], 1)[:, 0] > -big
        row_out = jnp.where(
            valid[:, None] & (jnp.arange(rows)[None] == ri[:, None]),
            ci[:, None].astype(row_out.dtype), row_out)
        col_out = jnp.where(
            valid[:, None] & (jnp.arange(cols)[None] == ci[:, None]),
            ri[:, None].astype(col_out.dtype), col_out)
        s = jnp.where(jnp.arange(rows)[None, :, None] == ri[:, None, None],
                      -big, s)
        s = jnp.where(jnp.arange(cols)[None, None, :] == ci[:, None, None],
                      -big, s)
        return (s, row_out, col_out), None

    init = (score, jnp.full((b, rows), -1.0, data.dtype),
            jnp.full((b, cols), -1.0, data.dtype))
    (_, row_out, col_out), _ = jax.lax.scan(one_round, init, None,
                                            length=n_rounds)
    return row_out, col_out


# -------------------------------------------------------------- misc -------

@register("_contrib_quadratic", aliases=("quadratic",))
def _quadratic(data, a=0.0, b=0.0, c=0.0):
    """parity: contrib/quadratic_op.cc (the extension-tutorial op)."""
    return a * jnp.square(data) + b * data + c


@register("_contrib_allclose", differentiable=False)
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    """parity: contrib/allclose_op.cc — 1.0 when `a` and `b` agree
    elementwise within rtol/atol (the test-suite comparison op)."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32)


@register("_contrib_index_array", differentiable=False)
def _index_array(data, axes=None):
    """parity: contrib/index_array.cc — coordinates of every element."""
    idx = jnp.stack(jnp.meshgrid(
        *[jnp.arange(s) for s in data.shape], indexing="ij"), axis=-1)
    if axes is not None:
        idx = idx[..., tuple(axes)]
    return idx.astype(jnp.int64)


@register("_contrib_getnnz", differentiable=False)
def _getnnz(data, axis=None):
    """parity: contrib/nnz.cc — count of structurally nonzero entries."""
    return jnp.sum(data != 0, axis=axis).astype(jnp.int64)


def _register_batchnorm_variants():
    """BatchNorm_v1 (legacy batch_norm_v1.cc) and SyncBatchNorm
    (contrib/sync_batch_norm.cc) both reduce to the one BatchNorm emitter:
    under pjit/GSPMD a batch-sharded mean/var already reduces GLOBALLY
    (XLA inserts the cross-device psum), so the 'sync' variant needs no
    separate communication path on TPU."""
    from . import nn as _nn

    bn = _nn._batch_norm
    register("BatchNorm_v1", num_outputs=3)(bn.fn)
    register("_contrib_SyncBatchNorm", num_outputs=3,
             aliases=("SyncBatchNorm",))(
        lambda data, gamma, beta, moving_mean, moving_var, key=None,
        ndev=1, **kw: bn.fn(data, gamma, beta, moving_mean, moving_var,
                            **{k: v for k, v in kw.items()
                               if k in ("eps", "momentum", "fix_gamma",
                                        "use_global_stats", "axis",
                                        "training", "output_mean_var")}))


_register_batchnorm_variants()


# ----------------------------------------------------------- image ops -----
# parity: src/operator/image/image_random.cc + resize.cc + crop.cc — the
# `npx.image` device-side pipeline (distinct from mx.image's host-side
# augmenters). Layout: HWC or NHWC, matching the reference.

@register("_image_to_tensor")
def _image_to_tensor(data):
    """HWC/NHWC uint8 [0,255] -> CHW/NCHW float32 [0,1]."""
    x = data.astype(jnp.float32) / 255.0
    perm = (2, 0, 1) if x.ndim == 3 else (0, 3, 1, 2)
    return jnp.transpose(x, perm)


@register("_image_normalize")
def _image_normalize(data, mean=(0.0,), std=(1.0,)):
    """CHW/NCHW normalize (runs after to_tensor, like the reference)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    m = jnp.asarray(mean, data.dtype).reshape(shape)
    s = jnp.asarray(std, data.dtype).reshape(shape)
    return (data - m) / s


@register("_image_resize")
def _image_resize(data, size=(), keep_ratio=False, interp=1):
    """HWC/NHWC resize; interp 0=nearest else bilinear."""
    if isinstance(size, int):
        size = (size, size)
    w, h = (size[0], size[1]) if len(size) == 2 else (size[0], size[0])
    method = "nearest" if interp == 0 else "linear"
    if data.ndim == 3:
        return jax.image.resize(data.astype(jnp.float32),
                                (h, w, data.shape[2]),
                                method=method).astype(data.dtype)
    return jax.image.resize(data.astype(jnp.float32),
                            (data.shape[0], h, w, data.shape[3]),
                            method=method).astype(data.dtype)


@register("_image_crop")
def _image_crop(data, x=0, y=0, width=1, height=1):
    """HWC/NHWC spatial crop at (x, y)."""
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]
