"""Neural-net ops: FC, Convolution, Pooling, Norms, Softmax, Dropout, RNN.

Parity target: `src/operator/nn/` in the reference (~32k LoC: hand-written
CPU kernels + cuDNN descriptors under `nn/cudnn/`). Here every op is one XLA
expression: convs lower to `lax.conv_general_dilated` (MXU), norms to fused
reduce+elementwise chains, RNN steps to `lax.scan`.

Data layouts keep MXNet semantics (NCHW / NCW / NCDHW, TNC for RNN). XLA's
layout assignment re-tiles for the MXU internally, so we do not hand-pick
NHWC the way cuDNN-era code does.

Stateful ops (BatchNorm running stats, Dropout RNG) are functional here:
BatchNorm returns (out, mean, var) and the Gluon layer carries the running
stats; Dropout takes an explicit PRNG key array (parity for the reference's
`FCreateOpState`/Resource kTempSpace+kRandom machinery,
`include/mxnet/resource.h:38-46`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from .registry import register


# ----------------------------------------------------------------- FC ------

@register("FullyConnected")
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True):
    """parity: src/operator/nn/fully_connected.cc. weight is (num_hidden, in)."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    # NOTE: no preferred_element_type=f32 here — the TPU MXU already
    # accumulates bf16 matmuls in f32, and an explicit f32 output + astype
    # breaks the vjp transpose (f32 cotangent vs bf16 operand).
    out = jax.lax.dot_general(
        data, weight,
        dimension_numbers=(((data.ndim - 1,), (1,)), ((), ())))
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ------------------------------------------------------------ Convolution --

def _conv_dims(kernel):
    return len(kernel)


def _tuplize(v, n):
    if v is None or v == ():
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@register("Convolution")
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                 num_filter=1, num_group=1, no_bias=False, layout=None,
                 cudnn_off=False, workspace=1024, cudnn_tune=None):
    """parity: src/operator/nn/convolution.cc (NCHW / NCW / NCDHW).

    weight layout: (num_filter, C/num_group, *kernel) as in the reference.
    """
    n = _conv_dims(kernel)
    stride = _tuplize(stride if stride else 1, n)
    dilate = _tuplize(dilate if dilate else 1, n)
    pad = _tuplize(pad if pad else 0, n)
    spatial = "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    out = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@register("Deconvolution")
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                   adj=(), target_shape=(), num_filter=1, num_group=1, no_bias=True,
                   layout=None, cudnn_off=False, workspace=1024, cudnn_tune=None):
    """parity: src/operator/nn/deconvolution.cc — transposed conv.

    weight layout (C_in, num_filter/num_group, *kernel) as in the reference.
    """
    n = _conv_dims(kernel)
    stride = _tuplize(stride if stride else 1, n)
    dilate = _tuplize(dilate if dilate else 1, n)
    pad = _tuplize(pad if pad else 0, n)
    adj = _tuplize(adj if adj else 0, n)
    spatial = "DHW"[3 - n:]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, ("NC" + spatial, "IO" + spatial, "NC" + spatial))
    # transposed conv = gradient of conv: lhs_dilation = stride
    pads = [(dilate[i] * (kernel[i] - 1) - pad[i],
             dilate[i] * (kernel[i] - 1) - pad[i] + adj[i]) for i in range(n)]
    # flip kernel spatial dims (transposed conv applies the mirrored filter)
    out = jax.lax.conv_general_dilated(
        data, jnp.flip(weight, axis=tuple(range(2, 2 + n))),
        window_strides=(1,) * n, padding=pads, lhs_dilation=stride,
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# --------------------------------------------------------------- Pooling ---

@register("Pooling", param_specs={
    "pool_type": {"choices": ("max", "avg", "sum", "lp"),
                  "doc": "Pooling reduction"},
    "pooling_convention": {"choices": ("valid", "full", "same")}})
def _pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
             global_pool=False, pooling_convention="valid", cudnn_off=False,
             count_include_pad=True, layout=None):
    """parity: src/operator/nn/pooling.cc via lax.reduce_window."""
    n = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * n
        pad = (0,) * n
    else:
        kernel = _tuplize(kernel, n)
        stride = _tuplize(stride if stride else 1, n)
        pad = _tuplize(pad if pad else 0, n)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    if pooling_convention == "full" and not global_pool:
        # ceil-mode output: pad on the high side so ceil division is achieved
        pads = [(0, 0), (0, 0)]
        for i in range(n):
            in_sz = data.shape[2 + i]
            out_sz = -(-(in_sz + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - in_sz - pad[i]
            pads.append((pad[i], max(needed, pad[i])))
    elif pooling_convention == "same" and not global_pool:
        # TF-style SAME: out = ceil(in/stride), asymmetric split padding
        pads = [(0, 0), (0, 0)]
        for i in range(n):
            in_sz = data.shape[2 + i]
            out_sz = -(-in_sz // stride[i])
            needed = max((out_sz - 1) * stride[i] + kernel[i] - in_sz, 0)
            pads.append((needed // 2, needed - needed // 2))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    # init values MUST be python scalar literals: array-valued inits break
    # reduce_window's vjp under jit (jax 0.9 linearization bug)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return jax.lax.reduce_window(data, init, jax.lax.max,
                                     window, strides, pads)
    if pool_type in ("avg", "sum"):
        zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
        summed = jax.lax.reduce_window(data, zero, jax.lax.add,
                                       window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = float(_np.prod(kernel))
            return summed / jnp.asarray(denom, data.dtype)
        ones = jnp.ones(data.shape, data.dtype)
        counts = jax.lax.reduce_window(ones, zero, jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        raise NotImplementedError("lp pooling")
    raise ValueError(f"unknown pool_type {pool_type}")


@register("_contrib_AdaptiveAvgPooling2D")
def _adaptive_avg_pool2d(data, output_size=(1, 1)):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    b, c, h, w = data.shape
    oh, ow = output_size
    # integral-image free path: only exact-divisor or degenerate cases fast
    x = data.reshape(b, c, oh, h // oh, ow, w // ow) if h % oh == 0 and w % ow == 0 \
        else None
    if x is not None:
        return x.mean(axis=(3, 5))
    # general case via interpolation-style gather
    hs = (jnp.arange(oh + 1) * h / oh).astype(jnp.int32)
    ws = (jnp.arange(ow + 1) * w / ow).astype(jnp.int32)
    rows = [data[:, :, hs[i]:hs[i + 1], :].mean(axis=2, keepdims=True) for i in range(oh)]
    x = jnp.concatenate(rows, axis=2)
    cols = [x[:, :, :, ws[j]:ws[j + 1]].mean(axis=3, keepdims=True) for j in range(ow)]
    return jnp.concatenate(cols, axis=3)


# ----------------------------------------------------------------- Norms ---

@register("BatchNorm", num_outputs=3)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, training=True):
    """parity: src/operator/nn/batch_norm.cc.

    Returns (out, batch_mean, batch_var); running-stat update is done by the
    caller (functional form — keeps the op pure for XLA).
    """
    axis = axis % data.ndim
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(-1 if i == axis else 1 for i in range(data.ndim))
    if training and not use_global_stats:
        mean = jnp.mean(data.astype(jnp.float32), axis=red_axes)
        var = jnp.var(data.astype(jnp.float32), axis=red_axes)
    else:
        mean, var = moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = jax.lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape).astype(data.dtype)) \
        * (g * inv.astype(g.dtype)).reshape(bshape) + beta.reshape(bshape)
    return out.astype(data.dtype), mean, var


@register("LayerNorm")
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm")
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5, output_mean_var=False):
    b, c = data.shape[:2]
    orig = data.shape
    x = data.reshape((b, num_groups, c // num_groups) + orig[2:])
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = ((x - mean) * jax.lax.rsqrt(var + eps)).reshape(orig)
    bshape = (1, c) + (1,) * (len(orig) - 2)
    return x * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm")
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(bshape) + beta.reshape(bshape)


@register("L2Normalization")
def _l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        kd = True
    elif mode == "channel":
        red, kd = (1,), True
    else:  # spatial
        red, kd = tuple(range(2, data.ndim)), True
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=kd) + eps)
    return data / norm


@register("LRN")
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(padded[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha / nsize * windows, beta)


# --------------------------------------------------------------- Softmax ---

@register("softmax")
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False):
    if temperature:
        data = data / temperature
    if use_length and length is not None:
        steps = jnp.arange(data.shape[axis])
        mask = steps < length[..., None]
        data = jnp.where(mask, data, -jnp.inf)
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def _log_softmax(data, axis=-1, temperature=None):
    if temperature:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin")
def _softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def _softmax_output_core(data, label, grad_scale, ignore_label, multi_output,
                         use_ignore, normalization, out_grad, smooth_alpha,
                         axis):
    return jax.nn.softmax(data, axis=axis)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, multi_output,
                        use_ignore, normalization, out_grad, smooth_alpha,
                        axis):
    out = jax.nn.softmax(data, axis=axis)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, multi_output, use_ignore,
                        normalization, out_grad, smooth_alpha, axis, res,
                        cot):
    """The reference's hand-written CE gradient (`softmax_output-inl.h`):
    d(data) = (softmax - onehot(label)) * grad_scale, with ignore-label
    masking and batch/valid normalization; head gradients are ignored
    unless out_grad=True (loss-head semantics)."""
    out, label = res
    num_classes = out.shape[axis]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), num_classes, axis=axis,
                            dtype=out.dtype)
    if smooth_alpha:
        onehot = onehot * (1.0 - smooth_alpha) + smooth_alpha / max(
            num_classes - 1, 1) * (1.0 - onehot)
    g = out - onehot
    valid = None
    if use_ignore:
        valid = (label != ignore_label).astype(out.dtype)
        g = g * jnp.expand_dims(valid, axis=axis)
    if normalization == "batch":
        g = g / label.shape[0]
    elif normalization == "valid":
        count = (jnp.sum(valid) if valid is not None
                 else jnp.asarray(label.size, out.dtype))
        g = g / jnp.maximum(count, 1.0)
    g = g * grad_scale
    if out_grad:
        g = g * cot
    return g.astype(out.dtype), jnp.zeros_like(label)


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("Softmax",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    """Forward = softmax; backward = the reference's custom cross-entropy
    gradient (p - onehot(label)) * grad_scale (`softmax_output.cc`), so the
    symbolic Module path trains exactly like the reference."""
    axis = 1 if multi_output else -1
    return _softmax_output_core(data, label, grad_scale, ignore_label,
                                multi_output, use_ignore, normalization,
                                out_grad, smooth_alpha, axis)


@register("Activation", param_specs={
    "act_type": {"choices": ("relu", "sigmoid", "tanh", "softrelu",
                             "softsign"),
                 "doc": "Activation function to apply"}})
def _activation(data, act_type="relu"):
    return {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
    }[act_type](data)


@register("LeakyReLU")
def _leaky_relu(data, gamma=None, key=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, training=False):
    """parity: src/operator/leaky_relu.cc — multi-mode activation
    (leaky/prelu/elu/selu/gelu/rrelu). `gamma` is the learned PReLU slope.
    rrelu draws U(lower, upper) slopes per element in training (pass a PRNG
    `key`); inference uses the deterministic midpoint slope."""
    if act_type == "rrelu" and training and key is not None:
        slopes = jax.random.uniform(key, data.shape, data.dtype,
                                    lower_bound, upper_bound)
        return jnp.where(data > 0, data, slopes * data)
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 1 else g
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, mid * data)
    raise ValueError(f"unknown LeakyReLU act_type {act_type}")


# --------------------------------------------------------------- Dropout ---

@register("Dropout", param_specs={
    "p": {"low": 0.0, "high": 1.0, "doc": "Fraction of units to drop"}})
def _dropout(data, key=None, p=0.5, mode="training", axes=(), training=True,
             cudnn_off=False):
    """parity: src/operator/nn/dropout-inl.h. `key` is a uint32 PRNG key array
    threaded by the caller (imperative: global generator; hybridized: per-call
    key input). Identity when not training or key is None."""
    if not training or key is None or p <= 0:
        return data
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape=tuple(shape))
    return jnp.where(mask, data / keep, jnp.zeros((), data.dtype))


# -------------------------------------------------------------- Losses -----

@register("MakeLoss")
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return data


@register("smooth_l1")
def _smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * jnp.square(data),
                     jnp.abs(data) - 0.5 / s2)


@register("CTCLoss", aliases=("ctc_loss",))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC forward-backward in log space via lax.scan (parity:
    src/operator/nn/ctc_loss.cc; 3rdparty/ctc_include warp-ctc).

    data: (T, B, V) unnormalised activations; label: (B, L) padded with -1
    (or 0 when blank_label='last' semantics match reference defaults).
    """
    T, B, V = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    blank = 0 if blank_label == "first" else V - 1
    lab = label.astype(jnp.int32)
    L = lab.shape[1]
    if label_lengths is not None and use_label_lengths:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        lab_len = jnp.sum(lab >= 0, axis=1).astype(jnp.int32)  # -1 padded
    if data_lengths is not None and use_data_lengths:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((B,), T, jnp.int32)
    # extended label sequence: blank a1 blank a2 ... blank  (len 2L+1)
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(lab >= 0, lab, blank))
    neg_inf = jnp.asarray(-1e30, jnp.float32)
    # alpha recursion
    a0 = jnp.full((B, S), neg_inf)
    a0 = a0.at[:, 0].set(logp[0, :, blank])
    first_lab = ext[:, 1]
    a0 = a0.at[:, 1].set(jnp.take_along_axis(logp[0], first_lab[:, None], axis=1)[:, 0])

    def logaddexp3(a, b, c):
        m = jnp.maximum(jnp.maximum(a, b), c)
        m = jnp.where(jnp.isfinite(m), m, 0.0)
        return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m) + jnp.exp(c - m))

    same = (ext == jnp.roll(ext, 2, axis=1)) | (ext == blank)

    def step(alpha, lp_t):
        shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same, neg_inf, shift2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new = logaddexp3(alpha, shift1, shift2) + emit
        return new, new

    _, alphas = jax.lax.scan(step, a0, logp[1:])
    alphas = jnp.concatenate([a0[None], alphas], axis=0)  # (T, B, S)
    tidx = (dat_len - 1).reshape(1, B, 1)
    a_last = jnp.take_along_axis(alphas, jnp.broadcast_to(tidx, (1, B, S)), axis=0)[0]
    end1 = jnp.take_along_axis(a_last, (2 * lab_len)[:, None], axis=1)[:, 0]
    end2 = jnp.take_along_axis(a_last, jnp.maximum(2 * lab_len - 1, 0)[:, None], axis=1)[:, 0]
    m = jnp.maximum(end1, end2)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    return -ll


# ------------------------------------------------------------------- RNN ---

@register("RNN", num_outputs=3)
def _rnn(data, params, state, state_cell=None, state_size=0, num_layers=1,
         mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
         projection_size=None, lstm_state_clip_min=None, lstm_state_clip_max=None,
         lstm_state_clip_nan=False, use_sequence_length=False, sequence_length=None):
    """Fused multi-layer RNN (parity: src/operator/rnn.cc:303, cuDNN RNN).

    data: (T, B, I) — TNC layout like the reference default.
    params: flat vector packed cuDNN-style per layer/direction:
        [W_x, W_h] for all gates, then all biases [b_x, b_h].
    Implemented as lax.scan over time per layer — the XLA-native analogue of
    the fused cuDNN kernel; XLA unrolls/pipelines the gate matmuls on MXU.
    """
    T, B, I = data.shape
    H = state_size
    ndir = 2 if bidirectional else 1
    ngates = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]

    def gate_act(x):
        return x

    offset = 0

    def take(n):
        nonlocal offset
        out = jax.lax.dynamic_slice(params, (offset,), (n,))
        offset += n
        return out

    # weights first (all layers), then biases — cuDNN packing order
    weights = []
    for layer in range(num_layers):
        for d in range(ndir):
            in_sz = I if layer == 0 else H * ndir
            wx = take(ngates * H * in_sz).reshape(ngates * H, in_sz)
            wh = take(ngates * H * H).reshape(ngates * H, H)
            weights.append((wx, wh))
    biases = []
    for layer in range(num_layers):
        for d in range(ndir):
            bx = take(ngates * H)
            bh = take(ngates * H)
            biases.append((bx, bh))

    def lstm_cell(carry, x_t, wx, wh, bx, bh):
        h, c = carry
        gates = x_t @ wx.T + h @ wh.T + bx + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        if lstm_state_clip_min is not None:
            c = jnp.clip(c, lstm_state_clip_min, lstm_state_clip_max)
        h = o * jnp.tanh(c)
        return (h, c), h

    def gru_cell(carry, x_t, wx, wh, bx, bh):
        (h,) = carry
        gx = x_t @ wx.T + bx
        gh = h @ wh.T + bh
        rx, zx, nx = jnp.split(gx, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        h = (1 - z) * n + z * h
        return (h,), h

    def vanilla_cell(carry, x_t, wx, wh, bx, bh):
        (h,) = carry
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu
        h = act(x_t @ wx.T + h @ wh.T + bx + bh)
        return (h,), h

    cell = {"lstm": lstm_cell, "gru": gru_cell,
            "rnn_relu": vanilla_cell, "rnn_tanh": vanilla_cell}[mode]

    x = data
    out_h, out_c = [], []
    for layer in range(num_layers):
        dir_outs = []
        for d in range(ndir):
            idx = layer * ndir + d
            wx, wh = weights[idx]
            bx, bh = biases[idx]
            h0 = state[idx]
            if mode == "lstm":
                carry0 = (h0, state_cell[idx])
            else:
                carry0 = (h0,)
            seq = jnp.flip(x, axis=0) if d == 1 else x

            def step(c, x_t):
                return cell(c, x_t, wx, wh, bx, bh)

            carry, ys = jax.lax.scan(step, carry0, seq)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outs.append(ys)
            out_h.append(carry[0])
            if mode == "lstm":
                out_c.append(carry[1])
        x = jnp.concatenate(dir_outs, axis=-1) if ndir == 2 else dir_outs[0]
    hn = jnp.stack(out_h, axis=0)
    cn = jnp.stack(out_c, axis=0) if mode == "lstm" else jnp.zeros_like(hn)
    return x, hn, cn


@register("UpSampling")
def _upsampling(*args, scale=1, sample_type="nearest", num_filter=0,
                multi_input_mode="concat", num_args=1, workspace=512):
    """parity: src/operator/nn/upsampling.cc — nearest/bilinear spatial
    upsampling. Nearest mode accepts MULTIPLE data inputs: each is scaled
    up to the first input's upsampled spatial size (its own factor =
    out_size / in_size), then channel-concatenated ('concat') or summed
    ('sum'). Bilinear mode takes (data, weight) and ignores the deconv
    weight — XLA's exact interpolation replaces the learned-kernel trick."""
    if sample_type != "nearest":
        data = args[0]
        n, c, h, w = data.shape
        return jax.image.resize(data, (n, c, h * scale, w * scale),
                                method="linear")
    out_h, out_w = args[0].shape[2] * scale, args[0].shape[3] * scale
    ups = []
    for i, d in enumerate(args):
        if out_h % d.shape[2] or out_w % d.shape[3]:
            raise ValueError(
                f"UpSampling: input {i} spatial {d.shape[2:]} does not "
                f"divide the target size ({out_h}, {out_w}) (= first input "
                f"* scale); the reference requires integer per-input scales")
        fh, fw = out_h // d.shape[2], out_w // d.shape[3]
        ups.append(jnp.repeat(jnp.repeat(d, fh, axis=2), fw, axis=3))
    if len(ups) == 1:
        return ups[0]
    if multi_input_mode == "sum":
        return sum(ups[1:], ups[0])
    return jnp.concatenate(ups, axis=1)


@register("Crop")
def _crop(data, like=None, offset=(0, 0), h_w=(0, 0), num_args=1,
          center_crop=False):
    """parity: src/operator/crop.cc — crop to `like`'s spatial size or an
    explicit h_w, at offset (or centered)."""
    if like is not None:
        th, tw = like.shape[2], like.shape[3]
    else:
        th, tw = h_w
    h, w = data.shape[2], data.shape[3]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = offset
    return data[:, :, oy:oy + th, ox:ox + tw]


@register("make_loss")
def _make_loss_op(data):
    """parity: make_loss (tensor/elemwise_unary_op_basic.cc) — identity
    marking a loss head."""
    return data


@register("relu6")
def _relu6(data):
    return jnp.clip(data, 0.0, 6.0)


@register("_contrib_BatchNormWithReLU", num_outputs=3)
def _batch_norm_with_relu(data, gamma, beta, moving_mean, moving_var,
                          **kwargs):
    """parity: contrib/batch_norm_relu.cc — BN + fused ReLU (XLA fuses the
    max into the BN elementwise epilogue on its own)."""
    out, mean, var = _batch_norm.fn(data, gamma, beta, moving_mean,
                                    moving_var, **kwargs)
    return jnp.maximum(out, 0), mean, var


def _register_sparse_embedding():
    """contrib/sparse_embedding -> the one Embedding emitter (row-sparse
    gradient handling lives in ndarray/sparse.py + the optimizers)."""
    from .registry import _REGISTRY

    emb = _REGISTRY["Embedding"]
    register("_contrib_SparseEmbedding")(emb.fn)


_register_sparse_embedding()
