"""Operator layer: registry + the op corpus.

Parity: `src/operator/` in the reference (~550 NNVM_REGISTER_OP entries).
Importing this package registers the full op set; consumers look ops up by
name via `ops.get(name)` (nnvm `Op::Get` analogue).
"""
from .registry import Operator, register, get, list_ops, apply_op, infer_output

from . import math  # noqa: F401  (registers elementwise/scalar/broadcast ops)
from . import tensor  # noqa: F401  (reduce/linalg/indexing/shape ops)
from . import nn  # noqa: F401  (FC/conv/pool/norm/softmax/rnn ops)
from . import optimizer_op  # noqa: F401  (fused optimizer updates)
from . import random_ops  # noqa: F401  (samplers)
from . import quantization  # noqa: F401  (int8 quantize/dequantize/conv/fc)
from . import numpy_ops  # noqa: F401  (_npi_* NumPy-frontend ops)
from . import la_op  # noqa: F401  (linalg_* suite)
from . import contrib_ops  # noqa: F401  (fft/detection/roi/stn/misc)
from . import output_ops  # noqa: F401  (regression/SVM loss heads)
from . import pallas_ops  # noqa: F401  (flash attention TPU kernel)
from . import custom  # noqa: F401  (Custom op — user-defined Python operators)

__all__ = ["Operator", "register", "get", "list_ops", "apply_op", "infer_output"]
