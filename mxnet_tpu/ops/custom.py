"""The ``Custom`` op — host for user-defined Python operators.

Parity: ``src/operator/custom/custom.cc`` (the C++ side that trampolines
into ``python/mxnet/operator.py`` callbacks via ctypes). The user API lives
in :mod:`mxnet_tpu.operator` (CustomOp / CustomOpProp / register); this
module owns the prop registry and the single registered op ``Custom`` that
``mx.nd.Custom(..., op_type=name)`` / ``mx.sym.Custom`` dispatch to.

TPU-native redesign: the reference runs custom-op callbacks on a dedicated
``CustomOperator`` worker thread pool inside the engine
(``src/operator/custom/custom-inl.h``). Here the op is a pure JAX function
whose body is a :func:`jax.pure_callback` — XLA stages a host callback into
the compiled program, so the same definition works eagerly, on the autograd
tape (via ``jax.custom_vjp`` calling the user's ``backward``), and inside
``hybridize``/Symbol executables. Shapes/dtypes come from the prop's
``infer_shape``/``infer_type`` exactly as the reference queries them
(``custom.cc:InferShape/InferType``).
"""
from __future__ import annotations

import functools

import numpy as np

from .registry import register

# op_type -> CustomOpProp subclass (filled by mxnet_tpu.operator.register)
CUSTOM_PROPS = {}


def _make_prop(op_type, kwargs):
    try:
        cls = CUSTOM_PROPS[op_type]
    except KeyError:
        raise ValueError(
            f"custom op type {op_type!r} is not registered; decorate your "
            "CustomOpProp subclass with mx.operator.register("
            f"{op_type!r})") from None
    prop = cls(**kwargs)
    prop._kwargs = dict(kwargs)
    return prop


def _host_ndarrays(np_arrays):
    """numpy -> NDArray (cpu) without touching the autograd tape."""
    from .. import autograd
    from ..ndarray import NDArray
    import jax.numpy as jnp

    with autograd.pause():
        return [NDArray(jnp.asarray(np.asarray(a))) for a in np_arrays]


def _host_forward(op, out_shapes, out_types, n_data, n_out, is_train,
                  *np_arrays):
    """Host side of the forward callback: allocate outputs, run the user's
    ``CustomOp.forward``, hand the buffers back to XLA. Shapes/dtypes and
    the operator instance were resolved once at trace time (the reference
    likewise caches the created operator, custom-inl.h)."""
    from .. import autograd

    with autograd.pause():
        arrays = _host_ndarrays(np_arrays)
        in_data, aux = arrays[:n_data], arrays[n_data:]
        from ..ndarray import zeros

        out_data = [zeros(tuple(s), dtype=t)
                    for s, t in zip(out_shapes, out_types)]
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=in_data, out_data=out_data, aux=aux)
        return tuple(np.asarray(o._data) for o in out_data)


def _host_backward(op, n_data, n_out, *np_arrays):
    """Host side of the backward callback.

    ``np_arrays`` = in_data+aux, out_data, out_grad (concatenated). Returns
    cotangents for every primal input (aux states get zeros, as in the
    reference where aux carries no gradient)."""
    from .. import autograd

    with autograd.pause():
        n_in_total = len(np_arrays) - 2 * n_out
        arrays = _host_ndarrays(np_arrays)
        ins, outs, cots = (arrays[:n_in_total],
                           arrays[n_in_total:n_in_total + n_out],
                           arrays[n_in_total + n_out:])
        in_data, aux = ins[:n_data], ins[n_data:]
        from ..ndarray import zeros

        in_grad = [zeros(a.shape, dtype=a.dtype) for a in in_data]
        op.backward(req=["write"] * n_data, out_grad=cots, in_data=in_data,
                    out_data=outs, in_grad=in_grad, aux=aux)
        zero_aux = [np.zeros(a.shape, dtype=a.dtype) for a in aux]
        return tuple([np.asarray(g._data) for g in in_grad] + zero_aux)


def _custom_num_outputs(n_inputs, static_kwargs):
    kwargs = {k: v for k, v in static_kwargs.items() if k != "op_type"}
    return len(_make_prop(static_kwargs["op_type"], kwargs).list_outputs())


def _custom_input_names(static_kwargs):
    kwargs = {k: v for k, v in static_kwargs.items() if k != "op_type"}
    prop = _make_prop(static_kwargs["op_type"], kwargs)
    return list(prop.list_arguments()) + list(prop.list_auxiliary_states())


def _freeze(v):
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, set)):
        return tuple(_freeze(x) for x in v)
    return v


# (op_type, kwargs, in_avals, is_train) -> compiled custom_vjp runner.
# The reference likewise creates the operator once per node and caches it
# (custom-inl.h CustomOperator); here the cache also skips re-running the
# prop's infer_shape/infer_type per invocation.
_RUNNER_CACHE = {}


@register("Custom", eager=True, num_outputs=_custom_num_outputs,
          input_names=_custom_input_names)
def custom(*arrays, op_type, **kwargs):
    """parity: src/operator/custom/custom.cc — inputs are the op's declared
    arguments followed by its auxiliary states; every kwarg is forwarded to
    the registered CustomOpProp constructor. ``is_train`` mirrors the
    reference's train_mode (autograd.is_training()), which is also the
    CachedOp executable-cache key, so traced executables never bake a stale
    mode."""
    from .. import autograd

    is_train = bool(autograd.is_training())
    sig = (op_type, _freeze(kwargs),
           tuple((tuple(a.shape), str(np.dtype(a.dtype))) for a in arrays),
           is_train)
    try:
        run, n_out = _RUNNER_CACHE[sig]
    except (KeyError, TypeError):
        run, n_out = _build_runner(op_type, kwargs, arrays, is_train)
        try:
            _RUNNER_CACHE[sig] = (run, n_out)
        except TypeError:
            pass  # unhashable kwarg — skip caching
    outs = run(*arrays)
    return outs if n_out > 1 else outs[0]


def _build_runner(op_type, kwargs, arrays, is_train):
    import jax

    prop = _make_prop(op_type, kwargs)
    n_data = len(prop.list_arguments())
    n_out = len(prop.list_outputs())

    in_shapes = [tuple(a.shape) for a in arrays[:n_data]]
    in_types = [np.dtype(a.dtype) for a in arrays[:n_data]]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    tt = prop.infer_type(in_types)
    out_types = [np.dtype(t) for t in tt[1]]
    out_avals = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                      for s, t in zip(out_shapes, out_types))
    in_avals = tuple(jax.ShapeDtypeStruct(tuple(a.shape), np.dtype(a.dtype))
                     for a in arrays)
    op_inst = prop.create_operator(None, in_shapes, in_types)

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(
            functools.partial(_host_forward, op_inst, out_shapes, out_types,
                              n_data, n_out, is_train),
            out_avals, *xs)

    def run_fwd(*xs):
        ys = run(*xs)
        return ys, (xs, ys)

    def run_bwd(res, cts):
        xs, ys = res
        return jax.pure_callback(
            functools.partial(_host_backward, op_inst, n_data, n_out),
            in_avals, *(tuple(xs) + tuple(ys) + tuple(cts)))

    run.defvjp(run_fwd, run_bwd)
    return run, n_out
