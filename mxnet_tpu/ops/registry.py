"""Operator registry — the op metadata layer.

Parity target: nnvm op registration in the reference
(``NNVM_REGISTER_OP`` + attributes ``FInferShape`` / ``FInferType`` /
``FCompute`` / ``FGradient``, `include/mxnet/op_attr_types.h:294,304`).
~550 ops are registered there, each hand-writing shape/type inference, CPU
and GPU kernels, and a gradient composition.

TPU-native redesign: every op is a *pure JAX function* ``fn(*arrays,
**static_kwargs) -> array(s)``.  That single definition supplies all the
nnvm attributes at once:

  * FCompute        -> the function itself, lowered by XLA to the device
  * FInferShape/Type-> ``jax.eval_shape`` on the function (no hand-written
                       inference pass; shapes are inferred by tracing)
  * FGradient       -> ``jax.vjp`` of the function (no hand-written grads)
  * kernel dispatch -> a per-(op, static-kwargs) ``jax.jit`` executable
                       cache: the "eager op cache" that makes imperative
                       mode non-blocking + fast, replacing the reference's
                       engine-push-per-op hot path
                       (`src/imperative/imperative_utils.h:396`).

Ops remain first-class registry entries (not bare Python functions) because
the graph layer (Symbol), the imperative tape, the AMP pass and opperf all
enumerate / look up ops by name, exactly as nnvm consumers do.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

from ..analysis import distcheck as _distcheck

__all__ = ["Operator", "register", "get", "list_ops", "apply_op", "infer_output"]

_REGISTRY: Dict[str, "Operator"] = {}


def _freeze(value):
    """Make kwargs hashable for the executable cache key."""
    if isinstance(value, dict):
        if len(value) == 1:  # scalar-op hot path: skip the sort machinery
            ((k, v),) = value.items()
            return ((k, _freeze(v)),)
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    if isinstance(value, set):
        return tuple(sorted(_freeze(v) for v in value))
    return value


class Operator:
    """A registered op: pure JAX fn + metadata.

    Attributes
    ----------
    fn : the pure function. All array arguments positional; every keyword
         argument is *static* (baked into the compiled executable) — the
         analogue of dmlc::Parameter op hyper-parameters.
    num_outputs : number of outputs (or None = single array). May be a
         callable ``(n_inputs, static_kwargs) -> int`` for ops whose output
         count depends on their hyper-parameters (split/SliceChannel,
         split_v2, Custom) — the symbol layer resolves it per node.
    differentiable : set False for ops with no gradient (e.g. argmax);
         the tape records them as constants.
    """

    def __init__(self, name: str, fn: Callable, num_outputs: Optional[int] = None,
                 differentiable: bool = True, aliases=(), eager: bool = False,
                 input_names: Optional[Callable] = None, param_specs=None):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.aliases = tuple(aliases)
        self.eager = eager  # dynamic-output-shape ops cannot be jitted
        # optional (static_kwargs) -> [input names] for *arrays ops whose
        # input list depends on hyper-parameters (Custom); lets the symbol
        # layer accept keyword Symbol inputs by declared name
        self.input_names = input_names
        self._param_specs = param_specs  # schema enrichment (range/doc)
        self._schema = None
        self._jit_cache: Dict = {}
        self._check_cache: Dict = {}
        self._partial_cache: Dict = {}  # kw_key -> fn with kwargs bound
        self._aval_cache: Dict = {}     # (kw_key, input avals) -> out avals

    @property
    def schema(self):
        """dmlc::Parameter analogue: the op's reflected parameter schema
        (ops/schema.py), derived from the fn signature + enrichment."""
        if self._schema is None:
            from .schema import OpSchema

            self._schema = OpSchema.from_fn(self.name, self.fn,
                                            self._param_specs)
        return self._schema

    def check_kwargs(self, kwargs: dict) -> dict:
        """Validate + string-coerce hyper-parameters (structured
        OpParamError instead of a TypeError deep inside a trace)."""
        return self.checked(kwargs)[0]

    def checked(self, kwargs: dict):
        """(validated_kwargs, frozen_key) — the key is shared with
        bound()'s jit cache so the imperative hot path freezes each
        kwargs dict ONCE per call; None when unhashable (array kwargs),
        meaning skip caching downstream. The returned dict is CACHED and
        shared across calls: callers must treat it as immutable (copy
        before storing anywhere that mutates, e.g. node attrs)."""
        if not kwargs:
            return kwargs, ()
        try:
            key = _freeze(kwargs)
            hit = self._check_cache.get(key)
            if hit is None:
                hit = self._check_cache[key] = self.schema.validate(kwargs)
            return hit, key
        except TypeError:
            # unhashable value (array kwarg) — validate without caching
            return self.schema.validate(kwargs), None

    def partial(self, kwargs: dict, key=False) -> Callable:
        """`fn` with these static kwargs bound, cached on the frozen key
        (one functools.partial per distinct hyper-parameter set — the
        imperative/bulking fast paths call this per op invocation)."""
        if not kwargs:
            return self.fn
        if key is False:
            try:
                key = _freeze(kwargs)
            except TypeError:
                key = None
        if key is None:
            return functools.partial(self.fn, **kwargs)
        hit = self._partial_cache.get(key)
        if hit is None:
            hit = self._partial_cache[key] = functools.partial(self.fn,
                                                               **kwargs)
        return hit

    def output_avals(self, in_sig, kwargs: dict, key):
        """(output ShapeDtypeStructs tuple, single?) for inputs with the
        given (shape, dtype) signature — cached abstract shape inference
        (FInferShape/FInferType for the bulking recorder: dispatch cost
        after the first call is one dict lookup, no tracing)."""
        sig = (key, in_sig)
        hit = self._aval_cache.get(sig)
        if hit is None:
            import jax

            outs = jax.eval_shape(self.partial(kwargs, key),
                                  *[jax.ShapeDtypeStruct(s, d)
                                    for s, d in in_sig])
            single = not isinstance(outs, (tuple, list))
            hit = self._aval_cache[sig] = (
                (outs,) if single else tuple(outs), single)
        return hit

    def bound(self, kwargs: dict, _key=False) -> Callable:
        """A jitted executable for these static kwargs (cached). `_key`
        is an optional precomputed `_freeze(kwargs)` (from `checked`);
        None means the kwargs are unhashable."""
        if self.eager:
            # data-dependent output shape (nonzero/unique/...): run the
            # emitter directly on concrete arrays, never under jit
            return self.partial(kwargs, _key)
        if _key is False:
            try:
                _key = _freeze(kwargs)
            except TypeError:
                _key = None
        if _key is None:
            # unhashable kwarg (e.g. array or traced value) — run eagerly
            return functools.partial(self.fn, **kwargs)
        key = _key
        try:
            hit = self._jit_cache[key]
        except KeyError:
            hit = None
        except TypeError:
            return functools.partial(self.fn, **kwargs)
        if _distcheck.CACHE_TRACK:
            # per-op dispatch-cache stats: the recompile-churn seam
            # (analysis.distcheck pass 4 / tools/diagnose.py)
            _distcheck.cache_event("dispatch", self.name, key,
                                   hit is not None)
        if hit is not None:
            return hit
        # the unified compile service (mxnet_tpu.compile): per-op hit/miss
        # + compile-ms metrics, persistent disk cache, AOT warmup — the
        # token (op name + frozen kwargs) is process-stable so warm starts
        # find prior executables
        from .. import compile as _compile

        jitted = _compile.jit(self.partial(kwargs, key), site="dispatch",
                              token=("op", self.name, key))
        self._jit_cache[key] = jitted
        return jitted

    def __call__(self, *arrays, **kwargs):
        return self.bound(kwargs)(*arrays)

    def __repr__(self):
        return f"Operator({self.name})"


def register(name: str, num_outputs: Optional[int] = None, differentiable: bool = True,
             aliases=(), eager: bool = False, input_names: Optional[Callable] = None,
             param_specs=None):
    """Decorator: register a pure JAX function as a named op.

    param_specs : optional {param: ParamSpec | dict} enriching the
        signature-derived schema with range/choices/doc metadata."""

    def deco(fn: Callable) -> Operator:
        op = Operator(name, fn, num_outputs=num_outputs,
                      differentiable=differentiable, aliases=aliases,
                      eager=eager, input_names=input_names,
                      param_specs=param_specs)
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return op

    return deco


_DTYPE_STR: Dict = {}


def dtype_str(dt) -> str:
    """Memoised str(dtype) — dispatch-path cache-key builders (CachedOp,
    bulking) stringify the same handful of dtype objects millions of
    times; one dict hit replaces repeated __str__ calls."""
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR[dt] = str(dt)
    return s


def get(name: str) -> Operator:
    try:
        return _REGISTRY[name]
    except KeyError:
        from ..base import did_you_mean

        raise KeyError(f"operator {name!r} is not registered "
                       f"({len(set(_REGISTRY.values()))} ops available)"
                       f"{did_you_mean(name, _REGISTRY, n=3)}") from None


def list_ops():
    return sorted({op.name for op in _REGISTRY.values()})


def op_schemas():
    """{op_name: schema dict} for every registered op — the reflected
    parameter-schema dump (doc generation, opperf arg synthesis; parity
    role: MXSymbolGetAtomicSymbolInfo's arg listing)."""
    return {name: get(name).schema.describe() for name in list_ops()}


def apply_op(name: str, *arrays, **kwargs):
    return get(name)(*arrays, **kwargs)


def infer_output(op: Operator, arrays, kwargs):
    """Shape/dtype inference without execution (parity: FInferShape/FInferType,
    `src/executor/infer_graph_attr_pass.cc:829`): trace with abstract values."""
    import jax

    return jax.eval_shape(functools.partial(op.fn, **kwargs), *arrays)
