"""Linear-algebra ops (parity: `src/operator/tensor/la_op.cc` — the
`linalg_*` suite over mshadow/cuSOLVER; here lowered to XLA's native
factorizations, which are MXU-tiled on TPU).

MXNet conventions preserved: batched over leading dims, `linalg_syevd`
returns eigenvectors as ROWS (A = U^T diag(L) U), `linalg_gelqf` yields
A = L Q with Q having orthonormal rows, `linalg_potri` computes the
inverse from a Cholesky factor.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _t(x):
    return jnp.swapaxes(x, -1, -2)


@register("linalg_gemm")
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    """parity: la_op.cc linalg_gemm — C = alpha*op(A)op(B) + beta*C."""
    a = _t(A) if transpose_a else A
    b = _t(B) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_potri")
def _linalg_potri(A, lower=True):
    """Inverse from a Cholesky factor: inv(B) where B = A A^T (lower).
    parity: la_op.cc linalg_potri."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    inv_l = jax.scipy.linalg.solve_triangular(A, eye, lower=lower)
    return jnp.matmul(_t(inv_l), inv_l) if lower \
        else jnp.matmul(inv_l, _t(inv_l))


@register("linalg_trmm")
def _linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Triangular matrix multiply (parity: la_op.cc linalg_trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = _t(tri)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register("linalg_trsm")
def _linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    """Triangular solve (parity: la_op.cc linalg_trsm): solves
    op(A) X = alpha B (or X op(A) = alpha B when rightside)."""
    if rightside:
        # X op(A) = aB  <=>  op(A)^T X^T = a B^T
        sol = jax.scipy.linalg.solve_triangular(
            A, _t(alpha * B), lower=lower, trans=0 if transpose else 1)
        return _t(sol)
    return jax.scipy.linalg.solve_triangular(
        A, alpha * B, lower=lower, trans=1 if transpose else 0)


@register("linalg_gelqf", num_outputs=2)
def _linalg_gelqf(A):
    """LQ factorization A = L Q (parity: la_op.cc linalg_gelqf)."""
    q, r = jnp.linalg.qr(_t(A))
    return _t(r), _t(q)


@register("linalg_syevd", num_outputs=2)
def _linalg_syevd(A):
    """Symmetric eigendecomposition, A = U^T diag(L) U with eigenvectors
    as rows (parity: la_op.cc linalg_syevd)."""
    w, v = jnp.linalg.eigh(A)
    return _t(v), w


@register("linalg_sumlogdiag")
def _linalg_sumlogdiag(A):
    """Sum of the logs of the main-diagonal entries of each matrix
    (parity: la_op.cc sumlogdiag)."""
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_extractdiag")
def _linalg_extractdiag(A, offset=0):
    """Extract the (offset) diagonal of each matrix as a vector
    (parity: la_op.cc extractdiag)."""
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def _linalg_makediag(A, offset=0):
    """Build square matrices carrying the input vectors on the (offset)
    diagonal (parity: la_op.cc makediag)."""
    base = jnp.zeros(A.shape[:-1] + (A.shape[-1] + abs(offset),) * 2,
                     A.dtype)
    idx = jnp.arange(A.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    return base.at[..., rows, cols].set(A)


@register("linalg_extracttrian")
def _linalg_extracttrian(A, offset=0, lower=True):
    """Extract the triangle as a packed vector (parity: la_op.cc)."""
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register("linalg_maketrian")
def _linalg_maketrian(A, offset=0, lower=True):
    """Unpack a packed triangle vector into a matrix (parity: la_op.cc;
    like the reference, offset > 0 implies the upper triangle and
    offset < 0 the lower one)."""
    import math

    m = A.shape[-1]
    k = abs(offset)
    # the packed triangle has t(t+1)/2 elements where t = n - k
    t = (math.isqrt(8 * m + 1) - 1) // 2
    n = t + k
    if offset > 0 or (offset == 0 and not lower):
        rows, cols = jnp.triu_indices(n, k=k)
    else:
        rows, cols = jnp.tril_indices(n, k=-k)
    base = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return base.at[..., rows, cols].set(A)


@register("linalg_det")
def _linalg_det(A):
    """Determinant of each matrix (parity: la_op.cc det)."""
    return jnp.linalg.det(A)


@register("linalg_slogdet", num_outputs=2)
def _linalg_slogdet(A):
    """Sign and log-abs-determinant of each matrix (parity: la_op.cc
    slogdet)."""
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register("linalg_inverse")
def _linalg_inverse(A):
    """Matrix inverse of each matrix (parity: la_op.cc inverse)."""
    return jnp.linalg.inv(A)


# `_linalg_*` aliases — the registered names in the reference
# (la_op.cc registers both `linalg_gemm` and the `_linalg_gemm` form).
def _register_linalg_aliases():
    from .registry import _REGISTRY, register as _reg

    for name in [n for n in list(_REGISTRY) if n.startswith("linalg_")]:
        alias = "_" + name
        if alias not in _REGISTRY:
            op = _REGISTRY[name]
            _reg(alias, num_outputs=op.num_outputs,
                 differentiable=op.differentiable, eager=op.eager)(op.fn)


_register_linalg_aliases()
