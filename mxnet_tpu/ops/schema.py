"""Per-op parameter schemas — the dmlc::Parameter layer (SURVEY §5.6).

Parity target: the reference declares every op's hyper-parameters through
``DMLC_DECLARE_PARAMETER`` blocks (exemplar:
`/root/reference/src/operator/control_flow.cc:35-59`), giving each op a
reflected schema used for keyword validation, string parsing on the C
boundary, error messages, and doc generation.

TPU-native redesign: every registered op is already a pure Python
function whose keyword arguments *are* its hyper-parameters, so the
schema is DERIVED from the function signature (name + default + type
inferred from the default) instead of hand-declared twice. Ops can
enrich the derived specs (range/choices/doc) through
``register(..., param_specs=...)``. The schema then provides:

* structured validation — unknown keywords raise ``OpParamError`` naming
  the op and listing its valid parameters (instead of a TypeError from
  deep inside a jit trace);
* dmlc-style string coercion — ``"2"`` -> 2, ``"(1, 2)"`` -> (1, 2),
  ``"True"`` -> True, matching how the reference parses parameter
  strings on the C ABI / symbol-JSON boundary;
* range/choices checks for enriched specs;
* ``describe()`` dumps — consumed by ``registry.op_schemas()`` and
  opperf arg synthesis.
"""
from __future__ import annotations

import ast
import inspect
from typing import Any, Dict, Optional

from ..base import MXNetError

__all__ = ["OpParamError", "ParamSpec", "OpSchema",
           "OPTIONAL_ARRAY_PARAMS", "RUNTIME_PARAMS"]

_REQUIRED = object()

# Signature params that are ARRAY INPUTS even though they default to None
# (optional weights/labels/keys) — used by OpSchema.from_fn to keep them
# out of the hyper-parameter dump. The symbol layer classifies inputs
# from Symbol-ness at compose time and consumes RUNTIME_PARAMS below;
# keep this set in sync with its expectations when adding ops.
OPTIONAL_ARRAY_PARAMS = frozenset(
    {"bias", "gamma", "beta", "moving_mean", "moving_var", "weight",
     "state", "state_cell", "label", "data_lengths", "label_lengths",
     "sequence_length", "lhs", "rhs", "mean", "var", "grad", "mom",
     "condition", "index", "indices", "a", "b", "x", "y", "data", "key"})

# Runtime-injected params — never graph inputs, never static attrs.
RUNTIME_PARAMS = frozenset({"key", "training"})


class OpParamError(MXNetError):
    """Invalid hyper-parameter for a registered op (structured analogue
    of dmlc::ParamError)."""

    def __init__(self, op_name, param, reason, valid=None):
        self.op_name = op_name
        self.param = param
        self.reason = reason
        msg = f"op {op_name!r}, parameter {param!r}: {reason}"
        if valid:
            msg += f"; valid parameters: {sorted(valid)}"
        super().__init__(msg)


class ParamSpec:
    """One hyper-parameter: name, inferred/declared type, default, and
    optional doc/range/choices enrichment."""

    __slots__ = ("name", "type", "default", "doc", "choices", "low", "high")

    def __init__(self, name, type=None, default=_REQUIRED, doc="",
                 choices=None, low=None, high=None):
        self.name = name
        self.type = type
        self.default = default
        self.doc = doc
        self.choices = tuple(choices) if choices is not None else None
        self.low = low
        self.high = high

    @property
    def required(self):
        return self.default is _REQUIRED

    def describe(self) -> Dict[str, Any]:
        out = {"name": self.name,
               "type": self.type.__name__ if self.type else "any"}
        if not self.required:
            out["default"] = self.default
        else:
            out["required"] = True
        if self.doc:
            out["doc"] = self.doc
        if self.choices is not None:
            out["choices"] = list(self.choices)
        if self.low is not None:
            out["low"] = self.low
        if self.high is not None:
            out["high"] = self.high
        return out

    # ------------------------------------------------------- validation ---
    def coerce(self, op_name, value):
        """dmlc-style scalar parsing + type/range/choices checks."""
        t = self.type
        was_string = isinstance(value, str) and t not in (None, str)
        if was_string:
            try:
                value = ast.literal_eval(value)
            except (ValueError, SyntaxError):
                raise OpParamError(
                    op_name, self.name,
                    f"cannot parse {value!r} as {t.__name__}") from None
        if t is bool and isinstance(value, int) and not isinstance(value, bool):
            value = bool(value)
        elif t is float and isinstance(value, int) and not isinstance(value, bool):
            value = float(value)
        elif t is int and isinstance(value, float) and value.is_integer():
            value = int(value)
        elif t in (tuple, list) and isinstance(value, (tuple, list)):
            value = t(value)
        # Type enforcement, dmlc-style but Python-polymorphism-aware:
        # a string that parsed to the wrong type, or a bare scalar where
        # a shape tuple is declared, raises HERE with op/param context
        # instead of a TypeError deep inside the jit trace. Other
        # mismatches pass — many params are deliberately polymorphic
        # (dtype accepts str or np.dtype; tensordot axes int or tuple).
        if t not in (None, object) and value is not None:
            wrong = not isinstance(value, t) and \
                not (t is float and isinstance(value, int))
            scalar_for_shape = t in (tuple, list) and \
                isinstance(value, (int, float, bool))
            if (was_string and wrong) or scalar_for_shape:
                raise OpParamError(
                    op_name, self.name,
                    f"expected {t.__name__}, got {type(value).__name__} "
                    f"({value!r})")
        if self.choices is not None and value not in self.choices:
            raise OpParamError(
                op_name, self.name,
                f"got {value!r}, expected one of {list(self.choices)}")
        if self.low is not None and isinstance(value, (int, float)) \
                and value < self.low:
            raise OpParamError(
                op_name, self.name, f"{value!r} is below minimum {self.low}")
        if self.high is not None and isinstance(value, (int, float)) \
                and value > self.high:
            raise OpParamError(
                op_name, self.name, f"{value!r} is above maximum {self.high}")
        return value


class OpSchema:
    """Array inputs + hyper-parameter specs of one op, derived from its
    function signature."""

    __slots__ = ("op_name", "inputs", "variadic", "params", "open_kwargs")

    def __init__(self, op_name, inputs, variadic, params, open_kwargs):
        self.op_name = op_name
        self.inputs = inputs          # positional array-input names
        self.variadic = variadic      # fn takes *arrays
        self.params = params          # {name: ParamSpec}
        self.open_kwargs = open_kwargs  # fn has **kw: accept any name

    @classmethod
    def from_fn(cls, op_name, fn, overrides: Optional[dict] = None):
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return cls(op_name, [], True, {}, True)
        inputs, params = [], {}
        variadic = open_kwargs = False
        for p in sig.parameters.values():
            if p.kind is inspect.Parameter.VAR_POSITIONAL:
                variadic = True
            elif p.kind is inspect.Parameter.VAR_KEYWORD:
                open_kwargs = True
            elif p.default is inspect.Parameter.empty:
                if p.kind is inspect.Parameter.KEYWORD_ONLY:
                    params[p.name] = ParamSpec(p.name)
                else:
                    inputs.append(p.name)
            elif p.default is None and p.name in OPTIONAL_ARRAY_PARAMS:
                # optional array input (bias/gamma/key/...), not a hyper
                inputs.append(p.name)
            else:
                d = p.default
                t = None if d is None else type(d)
                params[p.name] = ParamSpec(p.name, type=t, default=d)
        for name, extra in (overrides or {}).items():
            if name not in params and not open_kwargs:
                # a typo'd enrichment key would otherwise silently mint a
                # new accepted parameter AND leave the real one unchecked
                raise ValueError(
                    f"op {op_name!r}: param_specs entry {name!r} does not "
                    f"match any signature parameter {sorted(params)}")
            base = params.get(name) or ParamSpec(name)
            if isinstance(extra, ParamSpec):
                params[name] = extra
            else:
                for k, v in dict(extra).items():
                    setattr(base, k, v)
                params[name] = base
        return cls(op_name, inputs, variadic, params, open_kwargs)

    def validate(self, kwargs: dict) -> dict:
        """Check names, parse strings, apply range/choices. Returns the
        coerced kwargs (input dict is not mutated)."""
        if not kwargs:
            return kwargs
        out = {}
        for k, v in kwargs.items():
            spec = self.params.get(k)
            if spec is None:
                if self.open_kwargs or k in self.inputs:
                    out[k] = v
                    continue
                from ..base import did_you_mean

                reason = "unknown parameter" + did_you_mean(
                    k, list(self.params) + list(self.inputs))
                raise OpParamError(
                    self.op_name, k, reason, valid=self.params.keys())
            out[k] = spec.coerce(self.op_name, v)
        return out

    def describe(self) -> Dict[str, Any]:
        return {
            "op": self.op_name,
            "inputs": list(self.inputs) + (["*arrays"] if self.variadic
                                           else []),
            "params": [s.describe() for s in self.params.values()],
        }
