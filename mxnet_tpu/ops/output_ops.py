"""Loss-head output ops with the reference's hand-written gradients.

Parity: `src/operator/regression_output.cc` (LinearRegressionOutput :63,
MAERegressionOutput :84, LogisticRegressionOutput :74) and
`src/operator/svm_output.cc`. Forward is the prediction; backward ignores
head cotangents and injects the loss gradient directly — loss-head
semantics identical to SoftmaxOutput, so Module graphs train exactly like
the reference."""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _make_output_op(name, fwd_fn, grad_fn):
    @_functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label)

    def bwd(grad_scale, res, cot):
        out, label = res
        # reference normalizes by outputs-per-sample
        # (regression_output-inl.h: scale = grad_scale / num_output)
        num_output = out.size // out.shape[0] if out.ndim > 0 else 1
        g = grad_fn(out, label) * (grad_scale / num_output)
        return g.astype(out.dtype), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)

    @register(name)
    def op(data, label, grad_scale=1.0):
        """Regression output head: identity forward, loss-defined backward
        scaled by grad_scale (parity: regression_output.cc)."""
        lab = label.reshape(data.shape) if label.size == data.size \
            else label
        return core(data, lab, grad_scale)

    op.fn.__name__ = name
    return op


_make_output_op("LinearRegressionOutput",
                lambda d: d,
                lambda out, label: out - label)
_make_output_op("MAERegressionOutput",
                lambda d: d,
                lambda out, label: jnp.sign(out - label))
_make_output_op("LogisticRegressionOutput",
                jax.nn.sigmoid,
                lambda out, label: out - label)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_core(data, label, margin, reg_coef, use_linear):
    return data


def _svm_fwd(data, label, margin, reg_coef, use_linear):
    return data, (data, label)


def _svm_bwd(margin, reg_coef, use_linear, res, cot):
    """parity: svm_output-inl.h — L1/L2 hinge gradient on the true-class
    margin versus every other class."""
    data, label = res
    num_classes = data.shape[-1]
    onehot = jax.nn.one_hot(label.astype(jnp.int32), num_classes,
                            dtype=data.dtype)
    score_true = jnp.sum(data * onehot, axis=-1, keepdims=True)
    viol = margin - (score_true - data)  # margin violation per class
    viol = jnp.where(onehot > 0, 0.0, viol)
    if use_linear:
        mask = (viol > 0).astype(data.dtype)
        g_other = mask * reg_coef
    else:
        g_other = jnp.maximum(viol, 0.0) * 2.0 * reg_coef
    g_true = -jnp.sum(g_other, axis=-1, keepdims=True)
    g = g_other + g_true * onehot
    return g.astype(data.dtype), jnp.zeros_like(label)


_svm_core.defvjp(_svm_fwd, _svm_bwd)


@register("SVMOutput")
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """SVM output head: identity forward; backward is the (linear or
    squared) hinge-loss gradient (parity: svm_output.cc)."""
    return _svm_core(data, label, margin, regularization_coefficient,
                     use_linear)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _kl_sparse_core(data, sparseness_target, penalty, momentum):
    return data


def _kl_fwd(data, sparseness_target, penalty, momentum):
    return data, data


def _kl_bwd(sparseness_target, penalty, momentum, data, cot):
    """parity: src/operator/identity_attach_KL_sparse_reg.cc — identity
    forward; backward adds the KL sparsity penalty gradient on the mean
    activation rho_hat per hidden unit."""
    rho_hat = jnp.clip(jnp.mean(data, axis=0, keepdims=True), 1e-6,
                       1 - 1e-6)
    rho = sparseness_target
    kl_grad = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
    return (cot + kl_grad / data.shape[0]).astype(data.dtype),


_kl_sparse_core.defvjp(_kl_fwd, _kl_bwd)


@register("IdentityAttachKLSparseReg")
def _identity_attach_kl(data, sparseness_target=0.1, penalty=0.001,
                        momentum=0.9):
    """Identity forward that attaches a KL sparsity-penalty gradient on
    the mean activation (parity: identity_attach_KL_sparse_reg.cc)."""
    return _kl_sparse_core(data, sparseness_target, penalty, momentum)
