"""Elastic gang supervisor: multi-host rendezvous, reschedule/reshard/resume.

Parity target: the dmlc-tracker / ps-lite *scheduler* role in the reference
(SURVEY §L7) — the node above the workers that tracks liveness and restarts
dead ones. The TPU-native port has had every worker-side ingredient for a
while: the exit-code ladder (75 drain / 76 peer-lost / 86 watchdog abort /
137 kill, :mod:`mxnet_tpu.preempt`), topology-portable resharding
checkpoints (``CheckpointManager`` + ``ShardedTrainer.resume(reshard=)``),
and ``PeerLostError`` instead of wedged collectives. This module is the
layer that *consumes* them.

Three cooperating pieces:

* :class:`GangSupervisor` — spawns one worker process per gang slot with
  the per-rank rendezvous env (``MXTPU_COORDINATOR`` / ``MXTPU_WORKER_ID``
  / ``MXTPU_GANG_GENERATION``), watches them with a **monitor thread**
  (process exits + heartbeat files), and drives the gang state machine::

      RESUMING -> RUNNING -> DEGRADED -> RESCHEDULING -> RESUMING -> ...
                     |                                      (gen N+1)
                     +-> DONE (all ranks exit 0)
      any budget/census failure -> FAILED (+ structured post-mortem)

  A worker exiting with a *ladder* code (75/76/86/137) triggers a
  gang-wide coordinated restart at generation N+1: survivors are drained
  with SIGTERM (their preempt handlers checkpoint and exit 75), stragglers
  are SIGKILLed after a grace deadline, slots whose host/process was lost
  are dropped from the census (``shrink_on_kill``), surviving ranks are
  renumbered densely, and the next incarnation resumes from the last good
  checkpoint — on fewer hosts that resume *reshards* onto the smaller
  mesh. Restarts are budgeted (``max_restarts``) with exponential backoff;
  an exhausted budget writes a **post-mortem bundle** (per-generation exit
  codes, crash-bundle paths, drain events, per-rank heartbeat tails)
  instead of looping silently.

* **Heartbeat channel** — every worker runs a :func:`start_heartbeat`
  daemon that atomically rewrites ``rank-<r>.json`` in the shared run dir
  with its pid, generation, drain state, step count and the last
  watchdog/flight-recorder beat data. The supervisor reads the files to
  distinguish *slow* (heartbeats flowing, log a warning) from *dead*
  (heartbeats stopped while the process lives: SIGKILL it so the ladder
  takes over) without guessing.

* :func:`install_excepthook` — maps an uncaught exception carrying an
  integer ``exit_code`` attribute (``kvstore.PeerLostError`` sets 76) onto
  that process exit code, so the supervisor sees a ladder code instead of
  the interpreter's generic 1.

* :class:`ServingSupervisor` — the **serving mode** of the same
  machinery (``mxnet_tpu.serving.fleet`` drives it): slots restart
  *individually* instead of gang-wide, a deliberately drained worker
  (exit 75 after :meth:`ServingSupervisor.drain_slot` — rollout /
  scale-down) is retired rather than restarted, and slot ids are never
  reused so two model generations can overlap during a zero-downtime
  rollout. Heartbeat files, telemetry shards, the exit-code ladder and
  the liveness kill are shared verbatim with the gang path.

Environment knobs (supervisor side, CLI flags override)::

    MXNET_TPU_GANG_MAX_RESTARTS   restart budget across the run (default 5)
    MXNET_TPU_GANG_BACKOFF        first restart delay, seconds (default 1.0;
                                  doubles per restart)
    MXNET_TPU_GANG_BACKOFF_CAP    backoff ceiling, seconds (default 30)
    MXNET_TPU_GANG_GRACE          SIGTERM->SIGKILL escalation deadline (10)
    MXNET_TPU_GANG_DEAD_S         heartbeat-silence kill threshold for a
                                  live process (default 60; 0 disables)
    MXNET_TPU_GANG_BEAT           worker heartbeat period (default 2.0)
    MXNET_TPU_GANG_SHRINK         "1": drop killed/lost slots from the next
                                  generation's census (default keep)
    MXNET_TPU_GANG_DIR            run dir (default: a fresh tempdir)

Worker side (set by the supervisor): ``MXTPU_GANG_DIR``,
``MXTPU_GANG_GENERATION`` ride next to the ``MXTPU_COORDINATOR``
rendezvous vars; ``mxnet_tpu.__init__`` calls
:func:`maybe_install_from_env` so the heartbeat + excepthook arm
themselves in any worker launched by the supervisor.

Drive it from the CLI::

    python tools/launch.py --supervise -n 2 python train.py

Every recovery path is deterministically testable: the ``peerloss`` fault
mode (:mod:`mxnet_tpu.faults`) SIGKILLs a named peer rank from any
injection point, e.g. ``MXNET_TPU_FAULTS="trainer.step:peerloss@6:1"``.
"""
from __future__ import annotations

import json
import os
import signal as _signal
import subprocess
import sys
import tempfile
import threading
import time

from . import log as _log
from . import preempt as _preempt
from . import watchdog as _watchdog
from .telemetry import fleet as _fleet
from .telemetry import flight as _flight

__all__ = ["GangSupervisor", "ServingSupervisor", "RESTARTABLE_EXITS",
           "STATES", "STATE_CODES",
           "GANG_STATS", "start_heartbeat", "stop_heartbeat",
           "read_heartbeats", "kill_peer", "install_excepthook",
           "uninstall_excepthook", "maybe_install_from_env", "describe"]

_logger = _log.get_logger("mxnet_tpu.elastic")

# ------------------------------------------------------------ gang states --

IDLE = "idle"
RESUMING = "resuming"          # a generation is being (re)spawned
RUNNING = "running"            # all ranks alive
DEGRADED = "degraded"          # a rank was lost; draining the survivors
RESCHEDULING = "rescheduling"  # census/budget/backoff before gen N+1
DONE = "done"                  # every rank exited 0
FAILED = "failed"              # budget exhausted / fatal exit / no slots
STOPPED = "stopped"            # the supervisor itself was signalled

STATES = (IDLE, RESUMING, RUNNING, DEGRADED, RESCHEDULING, DONE, FAILED,
          STOPPED)
STATE_CODES = {s: i for i, s in enumerate(STATES)}
STATE_CODES["worker"] = len(STATES)  # worker-side: not supervising

#: ladder exits that mean "reschedule the gang", not "the job is broken"
RESTARTABLE_EXITS = frozenset({_preempt.DRAIN_EXIT_CODE,          # 75
                               _preempt.PEERLOST_EXIT_CODE,       # 76
                               _watchdog.ABORT_EXIT_CODE,         # 86
                               137,                               # SIGKILL
                               255})  # ssh transport lost == host lost

#: slot-lost exits: with ``shrink_on_kill`` these drop the slot from the
#: next generation's census (75/86 drained cleanly — the slot is fine)
_SLOT_LOST_EXITS = frozenset({137, 255})

# process-lifetime aggregates, read by the telemetry 'gang' collector at
# scrape time (mxtpu_gang_generation / mxtpu_gang_restarts_total{reason}
# / ...) — plain dict updates, mirroring kvstore.OP_COUNTS
GANG_STATS = {"state": IDLE, "generation": 0, "restarts": {},
              "restarts_total": 0, "degraded_s": 0.0, "workers_alive": 0,
              "postmortems": 0}


# Shared control-plane primitives live in cluster.py since the PR 19
# consolidation — these names stay as the compat surface every caller
# (and the concur analyzer's seam registry) already knows.

def _env_float(name, default):
    from .cluster import env_float

    return env_float(name, default)


def _env_int(name, default):
    from .cluster import env_int

    return env_int(name, default)


def _atomic_json(path, obj):
    """tmp + os.replace JSON write. Deliberately NOT checkpoint.atomic_write:
    gang state must stay recordable even while the ``ckpt.write`` fault
    point is armed — the supervisor records *other* processes' failures.
    Delegates to cluster.atomic_record, the one pid+thread-ident-safe
    seam the whole control plane shares."""
    from .cluster import atomic_record

    return atomic_record(path, obj)


# ------------------------------------------------- worker heartbeat side ---

_RANK_FILE = "rank-{rank}.json"
_heartbeater = None
_hb_lock = threading.Lock()


class _Heartbeater:
    """Daemon thread atomically rewriting this rank's status file."""

    def __init__(self, run_dir, rank, generation, interval):
        self.run_dir = os.fspath(run_dir)
        self.rank = int(rank)
        self.generation = int(generation)
        self.interval = max(0.05, float(interval))
        self.path = os.path.join(self.run_dir,
                                 _RANK_FILE.format(rank=self.rank))
        self._stop = threading.Event()
        self._warned = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxtpu-gang-beat")

    def _payload(self):
        from . import cluster as _cluster

        beats = _watchdog.heartbeats()
        return {"rank": self.rank, "pid": os.getpid(),
                "start_ticks": _cluster.proc_start_ticks(os.getpid()),
                "generation": self.generation,
                "t_wall": time.time(), "t_mono": time.monotonic(),
                "state": "draining" if _preempt.requested() else "running",
                "steps": _flight.counts().get("step.end", 0),
                "last_beat": beats[-1] if beats else None,
                "flight_tail": _flight.tail(8)}

    def beat(self):
        try:
            os.makedirs(self.run_dir, exist_ok=True)
            _atomic_json(self.path, self._payload())
        except OSError as e:
            if not self._warned:  # a broken shared dir must not spam
                self._warned = True
                _logger.warning("gang: heartbeat write failed: %s", e)
            return
        try:
            # the telemetry shard rides the same cadence: this rank's
            # post-collection metrics + step records + span/flight tails
            # (the fleet scrape, straggler verdict and merged gang trace
            # all read these; telemetry-off skips the write entirely)
            _fleet.write_shard(self.run_dir, self.rank, self.generation)
        except Exception as e:
            if not self._warned:
                self._warned = True
                _logger.warning("gang: telemetry shard write failed: %s",
                                e)

    def start(self):
        self.beat()  # announce immediately: the supervisor wants our pid
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


def start_heartbeat(run_dir, rank, generation=1, interval=None):
    """Start (or retarget) this process's gang heartbeat daemon. Returns
    the heartbeater; idempotent for identical coordinates."""
    global _heartbeater
    if interval is None:
        interval = _env_float("MXNET_TPU_GANG_BEAT", 2.0)
    with _hb_lock:
        hb = _heartbeater
        if hb is not None:
            if (hb.run_dir == os.fspath(run_dir) and hb.rank == int(rank)
                    and hb.generation == int(generation)):
                return hb
            hb.stop()
        _heartbeater = _Heartbeater(run_dir, rank, generation,
                                    interval).start()
        return _heartbeater


def stop_heartbeat():
    """Stop the heartbeat daemon (tests / clean worker exit)."""
    global _heartbeater
    with _hb_lock:
        if _heartbeater is not None:
            _heartbeater.stop()
            _heartbeater = None


def final_beat():
    """Write one heartbeat synchronously, right now (no-op when no
    daemon is armed). The drain terminal calls this before exiting: a
    worker that drains faster than the daemon's cadence must still
    leave ``state: draining`` on disk, because a supervisor restarted
    after an outage classifies adopted orphans' exits from exactly this
    evidence (75 on drain evidence, 137 otherwise)."""
    with _hb_lock:
        hb = _heartbeater
    if hb is not None:
        hb.beat()


def read_heartbeats(run_dir):
    """Parse every ``rank-<r>.json`` under `run_dir` into ``{rank: record}``
    with an ``age_s`` field (wall-clock since the last beat). Torn or
    unreadable files are skipped — the writer is mid-replace."""
    out = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    now = time.time()
    for name in names:
        if not (name.startswith("rank-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(run_dir, name)) as f:
                rec = json.load(f)
            rec["age_s"] = round(now - float(rec.get("t_wall", 0.0)), 3)
            out[int(rec["rank"])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def kill_peer(rank, run_dir=None, sig=_signal.SIGKILL):
    """SIGKILL the gang peer holding `rank` (pid looked up through its
    heartbeat file) — the seedable ``peerloss`` fault mode's muscle, so
    gang drills are deterministic like every other injected fault."""
    run_dir = run_dir or os.environ.get("MXTPU_GANG_DIR")
    if rank is None:
        raise RuntimeError("kill_peer: no target rank — the peerloss "
                           "fault spec names it as the arg, e.g. "
                           "'kvstore.sync:peerloss@3:1'")
    if not run_dir:
        raise RuntimeError("kill_peer: no gang run dir (MXTPU_GANG_DIR "
                           "unset and no run_dir given) — peerloss only "
                           "works under a gang supervisor")
    path = os.path.join(run_dir, _RANK_FILE.format(rank=int(rank)))
    try:
        with open(path) as f:
            pid = int(json.load(f)["pid"])
    except (OSError, ValueError, KeyError) as e:
        raise RuntimeError(
            f"kill_peer: no heartbeat for rank {rank} in {run_dir!r} "
            f"({e}) — is the gang running with heartbeats enabled?") from e
    _flight.rec("gang.peer_kill", f"rank{rank}", f"pid {pid}")
    _logger.warning("gang: injected peer loss — SIGKILL rank %s (pid %d)",
                    rank, pid)
    os.kill(pid, sig)


# ------------------------------------------------- worker exit-code hook ---

_exit_fn = os._exit  # test seam
_prev_hook = None


def install_excepthook():
    """Map an uncaught exception carrying an integer ``exit_code``
    attribute (e.g. ``kvstore.PeerLostError`` -> 76) onto the process exit
    code, AFTER the normal traceback prints — so the supervisor sees a
    ladder code instead of the interpreter's generic 1."""
    global _prev_hook
    if _prev_hook is not None:
        return

    prev = sys.excepthook

    def _hook(tp, value, tb):
        prev(tp, value, tb)
        code = getattr(value, "exit_code", None)
        if isinstance(code, int) and not isinstance(value, SystemExit):
            _flight.rec("gang.exit_code", tp.__name__, code)
            try:
                sys.stdout.flush()
                sys.stderr.flush()
            except OSError:
                pass
            _exit_fn(code)

    _prev_hook = prev
    sys.excepthook = _hook


def uninstall_excepthook():
    global _prev_hook
    if _prev_hook is not None:
        sys.excepthook = _prev_hook
        _prev_hook = None


def maybe_install_from_env():
    """Arm the worker-side gang plumbing when launched by a supervisor
    (``MXTPU_GANG_DIR`` set): heartbeat daemon + exit-code excepthook.
    Called from ``mxnet_tpu/__init__`` — one env var arms the stack."""
    run_dir = os.environ.get("MXTPU_GANG_DIR")
    if not run_dir:
        return False
    rank = _env_int("MXTPU_WORKER_ID", 0)
    gen = _env_int("MXTPU_GANG_GENERATION", 1)
    start_heartbeat(run_dir, rank, gen)
    install_excepthook()
    # single-key dict stores, GIL-atomic; the supervisor's monitor-thread
    # writers live in a *different process* than this worker-side arm
    GANG_STATS["state"] = "worker"      # concur: atomic
    GANG_STATS["generation"] = gen      # concur: atomic
    return True


# ------------------------------------------------------------- supervisor --

class GangSupervisor:
    """Spawn, watch, and elastically restart a gang of worker processes.

    Parameters
    ----------
    command : argv list every worker runs (``launch.py`` remainder).
    num_workers : local-mode gang size (one process per rank, this host).
    hosts : ssh-mode census — one host per rank (mutually exclusive with
        `num_workers`; requires a shared filesystem for run_dir/ckpts).
    run_dir : shared gang directory (heartbeats, gang.json, post-mortems,
        children's crash bundles + drain events). Default:
        ``MXNET_TPU_GANG_DIR`` or a fresh tempdir.
    coordinator_port : base rendezvous port; generation N uses
        ``port + N - 1`` — a fresh coordinator epoch per incarnation so a
        stale gen-N-1 process can never rendezvous into gen N.
    shrink_on_kill : drop slots whose process/host was hard-lost (exit
        137 / ssh 255 / heartbeat-dead) from the next census — the
        resumed gang reshards onto the smaller mesh.
    env : extra environment overrides for every worker.
    popen : spawn seam (tests); defaults to ``subprocess.Popen``.
    """

    def __init__(self, command, num_workers=None, hosts=None, *,
                 run_dir=None, coordinator_port=9357, max_restarts=None,
                 backoff=None, backoff_cap=None, grace=None,
                 dead_after=None, poll=0.2, shrink_on_kill=None,
                 env=None, cwd=None, popen=None):
        if hosts:
            self.slots = [{"host": h} for h in hosts]
        else:
            if not num_workers or num_workers < 1:
                raise ValueError("GangSupervisor needs num_workers >= 1 "
                                 "or a host list")
            self.slots = [{"host": None} for _ in range(num_workers)]
        self.command = list(command)
        self.run_dir = os.fspath(
            run_dir or os.environ.get("MXNET_TPU_GANG_DIR")
            or tempfile.mkdtemp(prefix="mxtpu_gang_"))
        os.makedirs(self.run_dir, exist_ok=True)
        self.crash_dir = os.path.join(self.run_dir, "crash")
        self.coordinator_port = int(coordinator_port)
        self.max_restarts = (_env_int("MXNET_TPU_GANG_MAX_RESTARTS", 5)
                             if max_restarts is None else int(max_restarts))
        self.backoff = (_env_float("MXNET_TPU_GANG_BACKOFF", 1.0)
                        if backoff is None else float(backoff))
        self.backoff_cap = (_env_float("MXNET_TPU_GANG_BACKOFF_CAP", 30.0)
                            if backoff_cap is None else float(backoff_cap))
        self.grace = (_env_float("MXNET_TPU_GANG_GRACE", 10.0)
                      if grace is None else float(grace))
        self.dead_after = (_env_float("MXNET_TPU_GANG_DEAD_S", 60.0)
                           if dead_after is None else float(dead_after))
        self.poll = max(0.02, float(poll))
        if shrink_on_kill is None:
            shrink_on_kill = os.environ.get("MXNET_TPU_GANG_SHRINK",
                                            "0") not in ("0", "", "false")
        self.shrink_on_kill = bool(shrink_on_kill)
        self.extra_env = dict(env or {})
        self.cwd = cwd
        self._popen = popen or subprocess.Popen

        # fleet aggregation: any /metrics scrape in this process (the
        # launch.py --metrics-port MetricsServer) now folds the rank
        # shards into mxtpu_fleet_* / mxtpu_gang_straggler_* series; the
        # monitor loop feeds the SAME detector so the straggler verdict
        # (and its flight event) exists even when nobody scrapes
        _fleet.install(self.run_dir)
        self._straggler = _fleet.detector()
        self._straggler_at = 0.0

        self.state = IDLE
        self.state_history = []        # [(t_wall, state)]
        self.generation = 0
        self.restarts_used = 0
        self.history = []              # one record per incarnation
        self.postmortem_path = None
        self._procs = {}               # rank -> Popen
        self._exits = {}               # rank -> canonical exit code
        self._liveness_killed = set()
        self._slow_warned = set()
        self._stop_signals = 0
        self._degraded_since = None
        self.degraded_s = 0.0
        self._rc = None

    # ------------------------------------------------------------- state --

    def _set_state(self, state):
        if state == self.state:
            return
        self.state = state
        self.state_history.append((time.time(), state))
        _flight.rec("gang.state", state, f"gen{self.generation}")
        GANG_STATS["state"] = state
        GANG_STATS["generation"] = self.generation
        if state == DEGRADED:
            self._degraded_since = time.monotonic()
        elif self._degraded_since is not None:
            self.degraded_s += time.monotonic() - self._degraded_since
            GANG_STATS["degraded_s"] = round(self.degraded_s, 3)
            self._degraded_since = None
        _logger.info("gang: state -> %s (generation %d)", state,
                     self.generation)
        self._write_summary()

    def describe(self):
        """Current gang state as a plain dict (gang.json / diagnose.py /
        the telemetry collector)."""
        return {"state": self.state, "generation": self.generation,
                "restarts_used": self.restarts_used,
                "max_restarts": self.max_restarts,
                "slots": [dict(s) for s in self.slots],
                "straggler": self._straggler.last,
                "run_dir": self.run_dir,
                "coordinator_port": self.coordinator_port,
                "shrink_on_kill": self.shrink_on_kill,
                "degraded_s": round(self.degraded_s, 3),
                "postmortem": self.postmortem_path,
                "history": self.history,
                "state_history": [
                    {"t_wall": t, "state": s}
                    for t, s in self.state_history]}

    def _write_summary(self):
        try:
            rec = self.describe()
            rec["updated"] = time.time()
            _atomic_json(os.path.join(self.run_dir, "gang.json"), rec)
        except OSError as e:
            _logger.warning("gang: could not write gang.json: %s", e)

    # ------------------------------------------------------------- spawn --

    def _worker_env(self, rank, generation):
        env = dict(os.environ)
        env.update(self.extra_env)
        host = self.slots[0]["host"] or "127.0.0.1"
        # a fresh coordinator epoch per generation: stale processes from
        # the previous incarnation can never rendezvous into this one
        port = self.coordinator_port + (generation - 1)
        env["MXTPU_COORDINATOR"] = f"{host}:{port}"
        env["MXTPU_NUM_WORKERS"] = str(len(self.slots))
        env["MXTPU_WORKER_ID"] = str(rank)
        env["DMLC_NUM_WORKER"] = str(len(self.slots))
        env["DMLC_WORKER_ID"] = str(rank)
        env["MXTPU_GANG_DIR"] = self.run_dir
        env["MXTPU_GANG_GENERATION"] = str(generation)
        # one place to look after any kind of death (the post-mortem
        # scans these); explicit user settings win
        env.setdefault("MXNET_TPU_CRASH_DIR", self.crash_dir)
        env.setdefault("MXNET_TPU_PREEMPT_DIR", self.run_dir)
        # SIGTERM from the coordinated teardown must DRAIN the worker
        # (final checkpoint + exit 75), not kill it mid-step
        env.setdefault("MXNET_TPU_PREEMPT", "1")
        return env

    def _spawn_generation(self):
        self.generation += 1
        self._set_state(RESUMING)
        self._procs = {}
        self._exits = {}
        self._liveness_killed = set()
        self._slow_warned = set()
        rec = {"generation": self.generation, "started": time.time(),
               "ranks": {}, "exits": {}, "reason": None,
               "liveness_killed": [], "crash_bundles": []}
        for rank, slot in enumerate(self.slots):
            env = self._worker_env(rank, self.generation)
            if slot["host"] is None:
                proc = self._popen(self.command, env=env, cwd=self.cwd)
            else:
                argv = _ssh_argv(slot["host"], env, self.command,
                                 cwd=self.cwd)
                proc = self._popen(argv)
            self._procs[rank] = proc
            rec["ranks"][str(rank)] = {"pid": proc.pid,
                                       "host": slot["host"]}
            _flight.rec("gang.spawn", f"gen{self.generation}",
                        f"rank{rank} pid {proc.pid}")
        rec["coordinator"] = self._worker_env(0, self.generation)[
            "MXTPU_COORDINATOR"]
        self.history.append(rec)
        GANG_STATS["workers_alive"] = len(self._procs)
        _logger.info("gang: generation %d spawned (%d workers, "
                     "coordinator %s)", self.generation, len(self.slots),
                     rec["coordinator"])
        self._write_summary()

    # ------------------------------------------------------------- watch --

    def _reap(self):
        """Collect finished workers into self._exits (canonical codes)."""
        rec = self.history[-1]
        for rank, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            code = _preempt.canonical_exit(rc)
            del self._procs[rank]
            self._exits[rank] = code
            rec["exits"][str(rank)] = code
            kind = _preempt.classify_exit(code)
            _flight.rec("gang.exit", f"gen{self.generation}",
                        f"rank{rank}: {code} ({kind})")
            level = _logger.info if code == 0 else _logger.warning
            level("gang: rank %d exited %d (%s)", rank, code, kind)
        GANG_STATS["workers_alive"] = len(self._procs)

    def _check_heartbeats(self):
        """Slow-vs-dead via the heartbeat channel: a live process whose
        beats stopped for ``dead_after`` seconds is declared dead and
        SIGKILLed (the ladder takes over); at half that it is only *slow*
        and logged. Ranks that never beat (non-instrumented commands) are
        left to the process-exit path."""
        if not self.dead_after:
            return
        beats = read_heartbeats(self.run_dir)
        for rank, proc in list(self._procs.items()):
            hb = beats.get(rank)
            if hb is None or hb.get("generation") != self.generation:
                continue
            age = hb.get("age_s", 0.0)
            if age > self.dead_after:
                _logger.error(
                    "gang: rank %d heartbeat silent for %.1fs (> %gs) "
                    "with a live process — declaring it dead (SIGKILL)",
                    rank, age, self.dead_after)
                self._liveness_killed.add(rank)
                self.history[-1]["liveness_killed"].append(rank)
                _flight.rec("gang.heartbeat_lost", f"rank{rank}",
                            f"{age:.1f}s")
                _kill_quietly(proc, _signal.SIGKILL)
            elif age > self.dead_after / 2 and \
                    rank not in self._slow_warned:
                self._slow_warned.add(rank)
                _logger.warning(
                    "gang: rank %d is SLOW — last heartbeat %.1fs ago "
                    "(%s at step %s); it will be declared dead at %gs",
                    rank, age, hb.get("state"), hb.get("steps"),
                    self.dead_after)

    def _check_straggler(self):
        """Feed the fleet straggler detector from the monitor loop
        (throttled: shard reads are cheap but not free at a 0.2s poll).
        A persistent straggler records its ``gang.straggler`` flight
        event here even when no scrape endpoint is mounted."""
        now = time.monotonic()
        if now - self._straggler_at < 1.0:
            return
        self._straggler_at = now
        try:
            self._straggler.update(
                _fleet.read_shards(self.run_dir,
                                   generation=self.generation))
        except Exception:
            pass  # telemetry must never take down supervision

    def _watch(self):
        """Monitor one generation. Returns ("done",), ("stop",),
        ("restart", reason) or ("fatal", code)."""
        first_cycle = True
        while True:
            if self._stop_signals:
                return ("stop",)
            self._reap()
            ladder = {r: c for r, c in self._exits.items()
                      if c in RESTARTABLE_EXITS}
            fatal = {r: c for r, c in self._exits.items()
                     if c != 0 and c not in RESTARTABLE_EXITS}
            if fatal:
                rank, code = sorted(fatal.items())[0]
                reason = (f"rank {rank} exited {code} "
                          f"({_preempt.classify_exit(code)})")
                self.history[-1]["reason"] = reason
                return ("fatal", code)
            if ladder:
                rank, code = sorted(ladder.items())[0]
                if rank in self._liveness_killed:
                    reason = f"rank {rank} heartbeat-lost"
                else:
                    reason = (f"rank {rank} exited {code} "
                              f"({_preempt.classify_exit(code)})")
                self.history[-1]["reason"] = reason
                return ("restart", reason)
            if not self._procs:
                return ("done",)
            if first_cycle:
                first_cycle = False
                self._set_state(RUNNING)
            self._check_heartbeats()
            self._check_straggler()
            time.sleep(self.poll)

    # ---------------------------------------------------------- teardown --

    def _teardown(self, graceful=True):
        """Coordinated stop of the remaining workers: SIGTERM (their
        preempt handlers drain: final checkpoint, exit 75), SIGKILL
        stragglers after the grace deadline."""
        if not self._procs:
            self.history[-1]["ended"] = time.time()
            self.history[-1]["crash_bundles"] = _list_bundles(
                self.crash_dir)
            return
        if graceful:
            _logger.warning(
                "gang: draining %d surviving worker(s) with SIGTERM "
                "(grace %gs)", len(self._procs), self.grace)
            for proc in self._procs.values():
                _kill_quietly(proc, _signal.SIGTERM)
            deadline = time.monotonic() + self.grace
            while self._procs and time.monotonic() < deadline:
                self._reap()
                if self._procs:
                    time.sleep(min(self.poll, 0.1))
        for rank, proc in list(self._procs.items()):
            _logger.error("gang: rank %d ignored the grace deadline — "
                          "SIGKILL", rank)
            _kill_quietly(proc, _signal.SIGKILL)
        deadline = time.monotonic() + 5.0
        while self._procs and time.monotonic() < deadline:
            self._reap()
            if self._procs:
                time.sleep(0.05)
        self.history[-1]["ended"] = time.time()
        self.history[-1]["crash_bundles"] = _list_bundles(self.crash_dir)

    def _shrink_census(self):
        """Drop slots whose process/host was hard-lost (137 / ssh 255 /
        heartbeat-dead); survivors are renumbered densely by position."""
        lost = {r for r, c in self._exits.items()
                if c in _SLOT_LOST_EXITS} | self._liveness_killed
        if not (self.shrink_on_kill and lost):
            return
        kept = [s for r, s in enumerate(self.slots) if r not in lost]
        self.history[-1]["shrunk"] = [
            {"rank": r, "host": self.slots[r]["host"] or "local"}
            for r in sorted(lost) if r < len(self.slots)]
        _logger.warning(
            "gang: census shrinks %d -> %d (lost rank(s) %s); surviving "
            "ranks renumbered densely", len(self.slots), len(kept),
            sorted(lost))
        self.slots = kept

    # -------------------------------------------------------- post-mortem --

    def _postmortem(self, reason):
        """The structured give-up bundle: what happened, generation by
        generation, with every diagnostic the run left behind."""
        drains = []
        try:
            for name in sorted(os.listdir(self.run_dir)):
                if name.startswith("drain-") and name.endswith(".json"):
                    try:
                        with open(os.path.join(self.run_dir, name)) as f:
                            ev = json.load(f)
                        ev["path"] = name
                        # the full flight tail is already in the bundle
                        ev.pop("flight_tail", None)
                        drains.append(ev)
                    except (OSError, ValueError):
                        continue
        except OSError:
            pass
        pm = {"reason": reason, "time": time.time(),
              "time_str": time.strftime("%Y-%m-%d %H:%M:%S"),
              "generation": self.generation,
              "restarts_used": self.restarts_used,
              "max_restarts": self.max_restarts,
              "backoff": self.backoff, "run_dir": self.run_dir,
              "slots": [dict(s) for s in self.slots],
              "generations": self.history,
              "state_history": [{"t_wall": t, "state": s}
                                for t, s in self.state_history],
              "heartbeats": read_heartbeats(self.run_dir),
              "crash_bundles": _list_bundles(self.crash_dir),
              "drain_events": drains,
              "supervisor_flight_tail": _flight.tail(64)}
        try:
            # the lock witness tail rides next to the flight tail: when
            # the run died wedged with MXNET_TPU_CONCUR_TRACE armed, the
            # post-mortem names the locks involved (analysis/concur)
            from .analysis import concur as _concur

            pm["witness_state"] = _concur.witness_state()
            pm["witness_tail"] = _concur.witness_tail()
        except Exception:
            pass
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(self.run_dir,
                            f"postmortem-{stamp}-p{os.getpid()}.json")
        try:
            _atomic_json(path, pm)
            self.postmortem_path = path
        except OSError as e:
            _logger.error("gang: failed to write post-mortem: %s", e)
        GANG_STATS["postmortems"] = GANG_STATS.get("postmortems", 0) + 1
        _flight.rec("gang.postmortem", reason, path)
        _logger.error("gang: giving up — %s; post-mortem: %s", reason,
                      self.postmortem_path or "<unwritable>")
        return self.postmortem_path

    # --------------------------------------------------------------- run --

    def _record_restart(self, reason):
        kind = reason.split("(")[-1].rstrip(")") if "(" in reason \
            else "heartbeat-lost"
        GANG_STATS["restarts"][kind] = \
            GANG_STATS["restarts"].get(kind, 0) + 1
        GANG_STATS["restarts_total"] = \
            GANG_STATS.get("restarts_total", 0) + 1

    def _supervise(self):
        while True:
            self._spawn_generation()
            outcome = self._watch()
            if outcome[0] == "done":
                self._set_state(DONE)
                _logger.info("gang: all ranks completed (generation %d, "
                             "%d restart(s))", self.generation,
                             self.restarts_used)
                return 0
            if outcome[0] == "stop":
                self._set_state(DEGRADED)
                self._teardown(graceful=self._stop_signals < 2)
                self._set_state(STOPPED)
                return _preempt.most_severe(self._exits.values())
            if outcome[0] == "fatal":
                self._set_state(DEGRADED)
                self._teardown()
                self._postmortem(self.history[-1]["reason"])
                self._set_state(FAILED)
                return _preempt.most_severe(self._exits.values())
            # outcome == ("restart", reason): the elastic path
            reason = outcome[1]
            self._set_state(DEGRADED)
            self._teardown()
            self._record_restart(reason)
            self._set_state(RESCHEDULING)
            if self.restarts_used >= self.max_restarts:
                self._postmortem(
                    f"restart budget exhausted ({self.restarts_used}/"
                    f"{self.max_restarts}) after: {reason}")
                self._set_state(FAILED)
                return 1
            self.restarts_used += 1
            self._shrink_census()
            if not self.slots:
                self._postmortem(f"no surviving slots after: {reason}")
                self._set_state(FAILED)
                return 1
            from .cluster import next_backoff
            delay = next_backoff(self.backoff, self.backoff_cap,
                                 self.restarts_used)
            _flight.rec("gang.restart", f"gen{self.generation + 1}",
                        reason)
            _logger.warning(
                "gang: coordinated restart %d/%d in %.1fs — %s "
                "(generation %d -> %d, %d slot(s))", self.restarts_used,
                self.max_restarts, delay, reason, self.generation,
                self.generation + 1, len(self.slots))
            end = time.monotonic() + delay
            while time.monotonic() < end and not self._stop_signals:
                time.sleep(min(0.1, end - time.monotonic()))

    def run(self):
        """Supervise until DONE / FAILED / STOPPED; returns the exit code
        for the outer wrapper (0 done; ladder code when stopped while
        draining; the fatal child code; 1 on exhausted budget/census).
        Installs SIGTERM/SIGINT handlers when on the main thread: the
        first signal drains the gang gracefully, a second skips the
        grace."""
        # single-key store, GIL-atomic against the monitor thread's
        # equally-atomic _set_state stores; readers only snapshot
        GANG_STATS["state"] = self.state    # concur: atomic

        def _on_signal(signum, frame):
            self._stop_signals += 1
            _logger.warning("gang: supervisor received %s — %s",
                            _signal.Signals(signum).name,
                            "draining the gang" if self._stop_signals == 1
                            else "killing the gang NOW")

        prev = {}
        try:
            for s in (_signal.SIGTERM, _signal.SIGINT):
                prev[s] = _signal.signal(s, _on_signal)
        except ValueError:
            prev = {}  # non-main thread: stop() still works via the flag
        monitor = threading.Thread(target=self._run_monitor, daemon=True,
                                   name="mxtpu-gang-monitor")
        monitor.start()
        try:
            while monitor.is_alive():
                monitor.join(timeout=0.2)
        finally:
            for s, h in prev.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, TypeError):
                    pass
            self._write_summary()
        return self._rc if self._rc is not None else 1

    def _run_monitor(self):
        try:
            self._rc = self._supervise()
        except Exception:
            _logger.exception("gang: supervisor monitor crashed")
            self._postmortem("supervisor crashed (see log)")
            self._set_state(FAILED)
            self._rc = 1

    def stop(self):
        """Request a graceful gang drain (same as SIGTERM)."""
        self._stop_signals += 1


# ------------------------------------------------- serving supervision ----

#: per-slot lifecycle states of a serving-mode supervisor
SLOT_STARTING = "starting"
SLOT_RUNNING = "running"
SLOT_DRAINING = "draining"     # deliberate drain requested (SIGTERM sent)
SLOT_BACKOFF = "backoff"       # crashed; restart scheduled
SLOT_FAILED = "failed"         # restart budget exhausted


class ServingSupervisor:
    """Serving-mode supervision: the fleet's process plane.

    The gang supervisor above restarts the WHOLE gang when one rank dies
    (training is a lockstep collective — a lost rank invalidates every
    survivor's step). Serving workers are independent replicas, so the
    policy inverts: each **slot** restarts individually, the others keep
    answering traffic, and a *deliberate* drain (rollout, scale-down)
    removes the slot instead of restarting it.

    Reuses the gang plumbing wholesale: workers get ``MXTPU_GANG_DIR`` /
    ``MXTPU_WORKER_ID`` / ``MXTPU_GANG_GENERATION`` so the heartbeat
    daemon + telemetry shard + exit-code excepthook arm themselves at
    ``import mxnet_tpu``; exits are classified through the same ladder
    (:func:`mxnet_tpu.preempt.canonical_exit`); heartbeat-silent live
    processes are declared dead and SIGKILLed exactly like gang ranks.

    Restart policy per serving semantics:

    * exit 75 on a slot marked draining — the **expected** drained-worker
      exit: the slot is retired (rollout/scale-down/stop), not restarted;
    * any other exit (ladder or not: a serving replica crashing with a
      real error should still come back — availability first) — restart
      the slot in place with exponential backoff, budgeted per slot;
      an exhausted budget parks the slot as ``failed`` with an event,
      it never flaps forever.

    Slot ids are **globally unique and never reused** (the fleet hands
    out a fresh id per spawn), so two generations can run side by side
    during a rollout without their ``rank-<r>.json`` heartbeat or
    telemetry shard files colliding.

    ``command_for(slot, generation)`` builds each worker's argv — the
    seam the fleet uses to point generation N+1 at a new model dir.
    Everything here is driven by :meth:`poll` from the owner's monitor
    loop; nothing blocks.
    """

    def __init__(self, command_for, run_dir, *, grace=None, dead_after=None,
                 backoff=None, backoff_cap=None, max_restarts=None,
                 env=None, cwd=None, popen=None):
        self.command_for = command_for
        self.run_dir = os.fspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.crash_dir = os.path.join(self.run_dir, "crash")
        self.grace = (_env_float("MXNET_TPU_GANG_GRACE", 10.0)
                      if grace is None else float(grace))
        self.dead_after = (_env_float("MXNET_TPU_GANG_DEAD_S", 60.0)
                           if dead_after is None else float(dead_after))
        self.backoff = (_env_float("MXNET_TPU_GANG_BACKOFF", 0.5)
                        if backoff is None else float(backoff))
        self.backoff_cap = (_env_float("MXNET_TPU_GANG_BACKOFF_CAP", 30.0)
                            if backoff_cap is None else float(backoff_cap))
        self.max_restarts = (_env_int("MXNET_TPU_GANG_MAX_RESTARTS", 5)
                             if max_restarts is None else int(max_restarts))
        self.extra_env = dict(env or {})
        self.cwd = cwd
        self._popen = popen or subprocess.Popen
        self._lock = threading.Lock()
        self.slots = {}            # slot -> record dict
        self.events = []           # lifecycle history (bounded)
        self.restarts_total = 0
        self.drained_total = 0

    # ------------------------------------------------------------- spawn --
    def _worker_env(self, slot, generation):
        env = dict(os.environ)
        env.update(self.extra_env)
        env["MXTPU_GANG_DIR"] = self.run_dir
        env["MXTPU_WORKER_ID"] = str(slot)
        env["MXTPU_GANG_GENERATION"] = str(generation)
        # serving workers are independent replicas: no rendezvous
        env.pop("MXTPU_COORDINATOR", None)
        env.setdefault("MXNET_TPU_CRASH_DIR", self.crash_dir)
        env.setdefault("MXNET_TPU_PREEMPT_DIR", self.run_dir)
        # SIGTERM must DRAIN the worker (answer everything admitted,
        # exit 75), never kill it mid-batch
        env.setdefault("MXNET_TPU_PREEMPT", "1")
        return env

    def _event(self, kind, slot, detail="", **extra):
        rec = {"t_wall": time.time(), "kind": kind, "slot": int(slot),
               "detail": detail}
        rec.update(extra)
        with self._lock:
            self.events.append(rec)
            del self.events[:-512]
        _flight.rec(f"fleet.{kind}", f"slot{slot}", detail)
        return rec

    def spawn(self, slot, generation):
        """Start one worker in `slot` (a fresh, never-reused id)."""
        slot = int(slot)
        proc = self._popen(self.command_for(slot, generation),
                           env=self._worker_env(slot, generation),
                           cwd=self.cwd)
        with self._lock:
            self.slots[slot] = {
                "slot": slot, "generation": int(generation), "proc": proc,
                "pid": proc.pid, "state": SLOT_STARTING,
                "spawned": time.time(), "restarts": 0, "exit_code": None,
                "restart_at": None, "liveness_killed": False}
        self._event("spawn", slot, f"gen{generation} pid {proc.pid}")
        _logger.info("fleet: slot %d spawned (generation %d, pid %d)",
                     slot, generation, proc.pid)
        return self.slots[slot]

    # ------------------------------------------------------------- drain --
    def drain_slot(self, slot, reason="drain"):
        """Deliberately retire `slot`: SIGTERM (its preempt handler
        answers everything admitted and exits 75); the reap removes the
        slot instead of restarting. Stragglers past the grace deadline
        are SIGKILLed by :meth:`poll`. A slot with no live process
        (backoff / failed) is retired on the spot."""
        with self._lock:
            rec = self.slots.get(int(slot))
            if rec is None or rec["state"] == SLOT_DRAINING:
                return rec
            proc = rec.get("proc")
            if proc is None:   # nothing running: retire immediately
                self.slots.pop(int(slot), None)
                self.drained_total += 1
            else:
                rec["state"] = SLOT_DRAINING
                rec["drain_reason"] = reason
                rec["drain_deadline"] = time.monotonic() + self.grace
        if proc is None:
            self._event("drained", slot,
                        f"retired while not running ({reason})",
                        exit_code=rec.get("exit_code"),
                        generation=rec["generation"])
            return rec
        self._event("drain", slot, reason)
        _kill_quietly(proc, _signal.SIGTERM)
        return rec

    def kill_slot(self, slot):
        """SIGKILL a slot's process (tests / chaos); the ladder reap and
        the per-slot restart policy take over."""
        with self._lock:
            rec = self.slots.get(int(slot))
            proc = rec.get("proc") if rec else None
        if proc is not None:
            _kill_quietly(proc, _signal.SIGKILL)
        return rec

    # -------------------------------------------------------------- poll --
    def _reap_one(self, slot, rec, code):
        kind = _preempt.classify_exit(code)
        rec["exit_code"] = code
        deliberate = rec["state"] == SLOT_DRAINING
        if deliberate and code in (0, _preempt.DRAIN_EXIT_CODE):
            with self._lock:
                self.slots.pop(slot, None)
                self.drained_total += 1
            self._event("drained", slot,
                        f"exit {code} ({rec.get('drain_reason')})",
                        exit_code=code, generation=rec["generation"])
            _logger.info("fleet: slot %d drained (exit %d)", slot, code)
            return
        if deliberate:
            # it ignored the drain and died some other way; still retired
            with self._lock:
                self.slots.pop(slot, None)
                self.drained_total += 1
            self._event("drain_killed", slot, f"exit {code} ({kind})",
                        exit_code=code, generation=rec["generation"])
            _logger.warning("fleet: draining slot %d exited %d (%s)",
                            slot, code, kind)
            return
        # an unrequested death: restart in place, budgeted, backed off
        if rec["restarts"] >= self.max_restarts:
            rec["state"] = SLOT_FAILED
            rec["proc"] = None
            self._event("slot_failed", slot,
                        f"exit {code} ({kind}); budget "
                        f"{rec['restarts']}/{self.max_restarts} exhausted",
                        exit_code=code)
            _logger.error("fleet: slot %d FAILED — exit %d (%s), restart "
                          "budget exhausted", slot, code, kind)
            return
        from .cluster import next_backoff
        delay = next_backoff(self.backoff, self.backoff_cap,
                             rec["restarts"] + 1)
        rec["restarts"] += 1
        rec["state"] = SLOT_BACKOFF
        rec["proc"] = None
        rec["restart_at"] = time.monotonic() + delay
        with self._lock:
            self.restarts_total += 1
        why = "heartbeat-lost" if rec.pop("liveness_killed", False) \
            else f"exit {code} ({kind})"
        self._event("restart", slot,
                    f"{why}; restart {rec['restarts']}/"
                    f"{self.max_restarts} in {delay:.1f}s",
                    exit_code=code)
        _logger.warning("fleet: slot %d died (%s) — restart %d/%d in "
                        "%.1fs", slot, why, rec["restarts"],
                        self.max_restarts, delay)

    def _check_heartbeats(self):
        if not self.dead_after:
            return
        beats = read_heartbeats(self.run_dir)
        for slot, rec in list(self.slots.items()):
            proc = rec.get("proc")
            if proc is None or rec["state"] == SLOT_DRAINING:
                continue
            hb = beats.get(slot)
            if hb is None or hb.get("generation") != rec["generation"]:
                continue  # never beat (or stale): the exit path owns it
            if hb.get("age_s", 0.0) > self.dead_after:
                rec["liveness_killed"] = True
                self._event("heartbeat_lost", slot,
                            f"{hb.get('age_s'):.1f}s silent")
                _logger.error("fleet: slot %d heartbeat silent %.1fs — "
                              "SIGKILL", slot, hb.get("age_s", 0.0))
                _kill_quietly(proc, _signal.SIGKILL)

    def poll(self):
        """One supervision pass: reap exits (apply the per-slot restart
        policy), escalate drain stragglers, kill heartbeat-dead workers,
        respawn slots whose backoff expired. Returns the live census
        ``{slot: record}`` (no Popen objects)."""
        now = time.monotonic()
        for slot, rec in list(self.slots.items()):
            proc = rec.get("proc")
            if proc is not None:
                rc = proc.poll()
                if rc is not None:
                    self._reap_one(slot, rec, _preempt.canonical_exit(rc))
                    continue
                if rec["state"] == SLOT_STARTING:
                    rec["state"] = SLOT_RUNNING
                if rec["state"] == SLOT_DRAINING and \
                        now >= rec.get("drain_deadline", now):
                    _logger.error("fleet: draining slot %d ignored the "
                                  "grace deadline — SIGKILL", slot)
                    rec["drain_deadline"] = now + self.grace
                    _kill_quietly(proc, _signal.SIGKILL)
            elif rec["state"] == SLOT_BACKOFF and \
                    now >= (rec.get("restart_at") or 0):
                gen = rec["generation"]
                restarts = rec["restarts"]
                newrec = self.spawn(slot, gen)
                newrec["restarts"] = restarts
        self._check_heartbeats()
        return self.census()

    # ------------------------------------------------------------- state --
    def census(self):
        """{slot: record-without-Popen} of every tracked slot."""
        out = {}
        with self._lock:
            for slot, rec in self.slots.items():
                r = {k: v for k, v in rec.items() if k != "proc"}
                r["alive"] = rec.get("proc") is not None \
                    and rec["proc"].poll() is None
                out[slot] = r
        return out

    def alive(self):
        """Slots with a live process right now."""
        return {s: r for s, r in self.census().items() if r["alive"]}

    def stop_all(self, graceful=True, timeout=None):
        """Retire every slot: drain (SIGTERM) then SIGKILL stragglers
        after the grace deadline; returns when all are reaped or
        `timeout` (default grace + 5s) expires. With ``graceful=False``
        slots are still MARKED draining before the SIGKILL — a stop
        must retire them, never trip the restart policy."""
        for slot in list(self.slots):
            self.drain_slot(slot, reason="stop")
            if not graceful:
                self.kill_slot(slot)
        deadline = time.monotonic() + (self.grace + 5.0
                                       if timeout is None else timeout)
        while self.slots and time.monotonic() < deadline:
            self.poll()
            if self.slots:
                time.sleep(0.05)
        for slot in list(self.slots):  # drainless stragglers
            self.kill_slot(slot)
            self.poll()
        return not self.slots

    def describe(self):
        """JSON-able supervisor state (fleet.json / diagnose)."""
        return {"run_dir": self.run_dir, "grace": self.grace,
                "dead_after": self.dead_after, "backoff": self.backoff,
                "max_restarts": self.max_restarts,
                "restarts_total": self.restarts_total,
                "drained_total": self.drained_total,
                "slots": self.census(),
                "events": list(self.events[-64:])}


def _kill_quietly(proc, sig):
    try:
        proc.send_signal(sig)
    except (ProcessLookupError, OSError):
        pass  # already gone: its exit code is about to be reaped


def _list_bundles(crash_dir):
    try:
        return sorted(os.path.join(crash_dir, n)
                      for n in os.listdir(crash_dir)
                      if n.startswith("bundle-"))
    except OSError:
        return []


def _ssh_argv(host, env, command, cwd=None, ssh_options=()):
    """Build the ssh argv for one remote worker: env rides inside the
    (fully shlex-quoted) remote command, ``-tt`` forces a tty so the
    remote process group is torn down when the local client is killed."""
    import shlex

    assigns = " ".join(
        f"{k}={shlex.quote(str(v))}" for k, v in sorted(env.items()))
    remote = (f"cd {shlex.quote(cwd or os.getcwd())} && "
              f"exec env {assigns} "
              + " ".join(shlex.quote(str(c)) for c in command))
    return (["ssh", "-o", "StrictHostKeyChecking=no", "-tt"]
            + list(ssh_options) + [host, remote])


def describe():
    """Module-level gang knobs + aggregates (diagnose.py)."""
    return {"stats": dict(GANG_STATS),
            "env": {k: os.environ.get(k) for k in
                    ("MXNET_TPU_GANG_MAX_RESTARTS",
                     "MXNET_TPU_GANG_BACKOFF",
                     "MXNET_TPU_GANG_BACKOFF_CAP",
                     "MXNET_TPU_GANG_GRACE", "MXNET_TPU_GANG_DEAD_S",
                     "MXNET_TPU_GANG_BEAT", "MXNET_TPU_GANG_SHRINK",
                     "MXNET_TPU_GANG_DIR", "MXTPU_GANG_DIR",
                     "MXTPU_GANG_GENERATION")},
            "heartbeat": None if _heartbeater is None else
            {"path": _heartbeater.path, "rank": _heartbeater.rank,
             "generation": _heartbeater.generation,
             "interval": _heartbeater.interval}}
