"""mx.np.linalg (parity: `python/mxnet/numpy/linalg.py` over
`src/operator/numpy/linalg/`). All factorizations lower to XLA's native
decompositions (cusolver analogues are built into XLA on TPU)."""
from __future__ import annotations

from ..ndarray.ndarray import _invoke
from . import _as_np, ndarray  # noqa: F401

__all__ = ["norm", "inv", "pinv", "det", "slogdet", "matrix_rank", "svd",
           "qr", "cholesky", "eig", "eigh", "eigvals", "eigvalsh", "solve",
           "lstsq", "matrix_power", "multi_dot", "tensorinv", "tensorsolve"]


def norm(x, ord=None, axis=None, keepdims=False):
    return _invoke("_npi_norm", [_as_np(x)],
                   {"ord": ord, "axis": axis, "keepdims": keepdims},
                   wrap=ndarray)


def inv(a):
    return _invoke("_npi_inv", [_as_np(a)], {}, wrap=ndarray)


def pinv(a, rcond=1e-15):
    return _invoke("_npi_pinv", [_as_np(a)], {"rcond": rcond}, wrap=ndarray)


def det(a):
    return _invoke("_npi_det", [_as_np(a)], {}, wrap=ndarray)


def slogdet(a):
    return _invoke("_npi_slogdet", [_as_np(a)], {}, wrap=ndarray)


def matrix_rank(M, tol=None):
    return _invoke("_npi_matrix_rank", [_as_np(M)], {"tol": tol},
                   wrap=ndarray)


def svd(a):
    return _invoke("_npi_svd", [_as_np(a)], {}, wrap=ndarray)


def qr(a):
    return _invoke("_npi_qr", [_as_np(a)], {}, wrap=ndarray)


def cholesky(a):
    return _invoke("_npi_cholesky", [_as_np(a)], {}, wrap=ndarray)


def eig(a):
    return _invoke("_npi_eig", [_as_np(a)], {}, wrap=ndarray)


def eigh(a, UPLO="L"):
    return _invoke("_npi_eigh", [_as_np(a)], {"UPLO": UPLO}, wrap=ndarray)


def eigvals(a):
    return _invoke("_npi_eigvals", [_as_np(a)], {}, wrap=ndarray)


def eigvalsh(a, UPLO="L"):
    return _invoke("_npi_eigvalsh", [_as_np(a)], {"UPLO": UPLO},
                   wrap=ndarray)


def solve(a, b):
    return _invoke("_npi_solve", [_as_np(a), _as_np(b)], {}, wrap=ndarray)


def lstsq(a, b, rcond=None):
    return _invoke("_npi_lstsq", [_as_np(a), _as_np(b)], {"rcond": rcond},
                   wrap=ndarray)


def matrix_power(a, n):
    return _invoke("_npi_matrix_power", [_as_np(a)], {"n": n}, wrap=ndarray)


def multi_dot(arrays):
    return _invoke("_npi_multi_dot", [_as_np(a) for a in arrays], {},
                   wrap=ndarray)


def tensorinv(a, ind=2):
    from ..ndarray.ndarray import _invoke_fn
    import jax.numpy as jnp

    return _invoke_fn(lambda x: jnp.linalg.tensorinv(x, ind=ind),
                      "tensorinv", [_as_np(a)], {}, wrap=ndarray)


def tensorsolve(a, b, axes=None):
    from ..ndarray.ndarray import _invoke_fn
    import jax.numpy as jnp

    return _invoke_fn(lambda x, y: jnp.linalg.tensorsolve(x, y, axes=axes),
                      "tensorsolve", [_as_np(a), _as_np(b)], {},
                      wrap=ndarray)


def tensorinv(a, ind=2):
    from ..ndarray.ndarray import _invoke

    from . import _as_np, ndarray

    return _invoke("_npi_tensorinv", [_as_np(a)], {"ind": int(ind)},
                   wrap=ndarray)


def tensorsolve(a, b, axes=None):
    from ..ndarray.ndarray import _invoke

    from . import _as_np, ndarray

    return _invoke("_npi_tensorsolve", [_as_np(a), _as_np(b)],
                   {"a_axes": tuple(axes) if axes else None}, wrap=ndarray)


def pinv(a, rcond=1e-15, hermitian=False):
    from ..ndarray.ndarray import _invoke

    from . import _as_np, ndarray

    return _invoke("_npi_pinv_scalar_rcond", [_as_np(a)],
                   {"rcond": float(rcond), "hermitian": bool(hermitian)},
                   wrap=ndarray)
