"""mx.np — the NumPy-compatible frontend.

Parity target: `python/mxnet/numpy/multiarray.py` (~12.6k LoC) over
`src/operator/numpy/` (`_npi_*` ops). `mx.np.ndarray` follows NumPy
semantics — zero-dim arrays, boolean masking, bool comparison results,
`@` matmul, NumPy type promotion — while staying a first-class framework
tensor: it lives on a Context, records on the autograd tape, hybridizes,
and its ops dispatch through the same registry (`ops/numpy_ops.py`) as
everything else, so AMP / profiler / opperf see them uniformly.
"""
from __future__ import annotations

import numpy as _onp

from ..context import current_context
from ..ndarray.ndarray import NDArray, _invoke, _invoke_fn

# re-exported numpy dtype/constant surface (parity: numpy/__init__.py)
from numpy import (float16, float32, float64, int8, int16, int32, int64,  # noqa: F401
                   uint8, uint16, uint32, uint64, bool_, pi, e, inf, nan,
                   euler_gamma, newaxis)

_npx_dtype = None


class ndarray(NDArray):
    """NumPy-semantics tensor (parity: numpy/multiarray.py ndarray)."""

    __slots__ = ()
    _np_frontend = True  # _invoke propagates this class through ops

    # ------------------------------------------------------------- repr ----
    def __repr__(self):
        arr = self.asnumpy()
        prefix = "array("
        body = _onp.array2string(arr, separator=", ", prefix=prefix)
        ctx = self.context
        suffix = f", ctx={ctx})" if ctx.device_type != "cpu" else ")"
        if arr.dtype not in (_onp.float32, _onp.int32, _onp.bool_):
            suffix = f", dtype={arr.dtype}" + suffix
        return prefix + body + suffix

    def __str__(self):
        return str(self.asnumpy())

    # ------------------------------------------- numpy dispatch protocol ---
    # parity: python/mxnet/numpy_dispatch_protocol.py (+ the
    # numpy_op_fallback.py escape hatch): numpy functions called on these
    # arrays dispatch to the mx.np implementation when one exists, else
    # fall back to real numpy and re-wrap, so the array type stays closed
    # under the whole numpy API.

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        out = kwargs.pop("out", None)
        if method == "at":
            # in-place index update: run on a host COPY, write back through
            # the functional rebind (never through the jax buffer's view)
            target = inputs[0]
            host = _onp.array(target.asnumpy())
            ufunc.at(host, *self._unwrap(tuple(inputs[1:])))
            target[:] = array(host, ctx=target.context)
            return None
        if out is not None and (kwargs or method != "__call__"):
            # let numpy apply the full out-semantics (where= keeps the out
            # array's prior values) on host copies, then rebind
            host_outs = tuple(_onp.array(t.asnumpy())
                              for t in (out if isinstance(out, tuple)
                                        else (out,)))
            kwargs["out"] = host_outs if len(host_outs) > 1 else host_outs[0]
            self._numpy_fallback(getattr(ufunc, method), inputs, kwargs)
            return self._fill_out(
                host_outs if len(host_outs) > 1 else array(host_outs[0]),
                out)
        if method != "__call__":
            result = self._numpy_fallback(getattr(ufunc, method), inputs,
                                          kwargs)
        elif not kwargs:
            # mx implementation only for the plain call — numpy-only kwargs
            # (where=, dtype=, casting=...) would be silently ignored by
            # the thin wrappers, so anything fancier falls back wholesale
            import sys

            fn = getattr(sys.modules[__name__], ufunc.__name__, None)
            if fn is not None:
                try:
                    result = fn(*inputs)
                except TypeError:
                    result = self._numpy_fallback(ufunc, inputs, kwargs)
            else:
                result = self._numpy_fallback(ufunc, inputs, kwargs)
        else:
            result = self._numpy_fallback(ufunc, inputs, kwargs)
        return self._fill_out(result, out)

    def __array_function__(self, func, types, args, kwargs):
        out = kwargs.pop("out", None)
        if out is None and kwargs.get("where") is None:
            import sys

            fn = getattr(sys.modules[__name__], func.__name__, None)
            if fn is not None and fn is not func:
                try:
                    return fn(*args, **kwargs)
                except TypeError:
                    pass
        return self._fill_out(self._numpy_fallback(func, args, kwargs), out)

    @staticmethod
    def _fill_out(result, out):
        """Honor the numpy out= contract: write the result INTO the given
        array (functional rebind) and return it."""
        if out is None:
            return result
        targets = out if isinstance(out, tuple) else (out,)
        results = result if isinstance(result, tuple) else (result,)
        for t, r in zip(targets, results):
            t[:] = r if isinstance(r, NDArray) else array(r)
        # ufuncs hand out= in as a 1-tuple; the call returns the bare array
        return targets[0] if len(targets) == 1 else out

    @staticmethod
    def _unwrap(args):
        def unwrap(x):
            if isinstance(x, NDArray):
                # copies, not views: numpy may write into its operands
                return _onp.array(x.asnumpy())
            if isinstance(x, (list, tuple)):
                return type(x)(unwrap(v) for v in x)
            return x

        return unwrap(tuple(args))

    @staticmethod
    def _numpy_fallback(func, args, kwargs):
        out = func(*ndarray._unwrap(tuple(args)),
                   **{k: ndarray._unwrap((v,))[0] for k, v in kwargs.items()})
        if isinstance(out, _onp.ndarray):
            return array(out)
        if isinstance(out, tuple):
            return tuple(array(o) if isinstance(o, _onp.ndarray) else o
                         for o in out)
        return out

    # -------------------------------------------------------- operators ----
    def _bin(self, other, op, scalar_op=None, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return _invoke(op, args, {}, wrap=ndarray)
        if scalar_op is not None and isinstance(other, (int, float, bool)):
            name = ("_npi_r" + scalar_op if reverse else
                    "_npi_" + scalar_op) + "_scalar"
            try:
                return _invoke(name, [self], {"scalar": other}, wrap=ndarray)
            except KeyError:
                pass
        other = array(other, ctx=self.context)
        args = [other, self] if reverse else [self, other]
        return _invoke(op, args, {}, wrap=ndarray)

    def __add__(self, o):
        return self._bin(o, "_npi_add", "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin(o, "_npi_subtract", "subtract")

    def __rsub__(self, o):
        return self._bin(o, "_npi_subtract", "subtract", reverse=True)

    def __mul__(self, o):
        return self._bin(o, "_npi_multiply", "multiply")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin(o, "_npi_true_divide", "true_divide")

    def __rtruediv__(self, o):
        return self._bin(o, "_npi_true_divide", "true_divide", reverse=True)

    def __floordiv__(self, o):
        return self._bin(o, "_npi_floor_divide", "floor_divide")

    def __rfloordiv__(self, o):
        return self._bin(o, "_npi_floor_divide", "floor_divide",
                         reverse=True)

    def __mod__(self, o):
        return self._bin(o, "_npi_mod", "mod")

    def __rmod__(self, o):
        return self._bin(o, "_npi_mod", "mod", reverse=True)

    def __pow__(self, o):
        return self._bin(o, "_npi_power", "power")

    def __rpow__(self, o):
        return self._bin(o, "_npi_power", "power", reverse=True)

    def __matmul__(self, o):
        return self._bin(o, "_npi_matmul")

    def __rmatmul__(self, o):
        return self._bin(o, "_npi_matmul", reverse=True)

    def __neg__(self):
        return _invoke("_npi_negative", [self], {}, wrap=ndarray)

    def __abs__(self):
        return _invoke("_npi_absolute", [self], {}, wrap=ndarray)

    def __invert__(self):
        return _invoke("_npi_invert", [self], {}, wrap=ndarray)

    def __eq__(self, o):
        return self._bin(o, "_npi_equal")

    def __ne__(self, o):
        return self._bin(o, "_npi_not_equal")

    def __lt__(self, o):
        return self._bin(o, "_npi_less")

    def __le__(self, o):
        return self._bin(o, "_npi_less_equal")

    def __gt__(self, o):
        return self._bin(o, "_npi_greater")

    def __ge__(self, o):
        return self._bin(o, "_npi_greater_equal")

    __hash__ = NDArray.__hash__

    def __and__(self, o):
        return self._bin(o, "_npi_bitwise_and")

    def __or__(self, o):
        return self._bin(o, "_npi_bitwise_or")

    def __xor__(self, o):
        return self._bin(o, "_npi_bitwise_xor")

    # --------------------------------------------------------- methods -----
    @property
    def T(self):
        return _invoke("_npi_transpose", [self], {}, wrap=ndarray)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke("_npi_transpose", [self],
                       {"axes": axes or None}, wrap=ndarray)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return _invoke("_npi_reshape", [self], {"newshape": shape},
                       wrap=ndarray)

    def flatten(self, order="C"):
        return _invoke("_npi_ravel", [self], {}, wrap=ndarray)

    ravel = flatten

    def astype(self, dtype, copy=True):
        return _invoke_fn(lambda x: x.astype(_npdt(dtype)), "astype", [self],
                          {}, wrap=ndarray)

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def as_nd_ndarray(self):
        """Convert to the legacy mx.nd frontend (parity: multiarray.py)."""
        out = NDArray(self._data)
        out._tape_node = self._tape_node
        out._tape_index = self._tape_index
        out._grad_req = self._grad_req
        out._grad = self._grad
        return out

    def sum(self, axis=None, dtype=None, keepdims=False):
        return _invoke("_npi_sum", [self],
                       {"axis": axis, "dtype": _npdt(dtype),
                        "keepdims": keepdims}, wrap=ndarray)

    def mean(self, axis=None, dtype=None, keepdims=False):
        return _invoke("_npi_mean", [self],
                       {"axis": axis, "dtype": _npdt(dtype),
                        "keepdims": keepdims}, wrap=ndarray)

    def std(self, axis=None, ddof=0, keepdims=False):
        return _invoke("_npi_std", [self], {"axis": axis, "ddof": ddof,
                                            "keepdims": keepdims},
                       wrap=ndarray)

    def var(self, axis=None, ddof=0, keepdims=False):
        return _invoke("_npi_var", [self], {"axis": axis, "ddof": ddof,
                                            "keepdims": keepdims},
                       wrap=ndarray)

    def prod(self, axis=None, keepdims=False):
        return _invoke("_npi_prod", [self], {"axis": axis,
                                             "keepdims": keepdims},
                       wrap=ndarray)

    def max(self, axis=None, keepdims=False):
        return _invoke("_npi_max", [self], {"axis": axis,
                                            "keepdims": keepdims},
                       wrap=ndarray)

    def min(self, axis=None, keepdims=False):
        return _invoke("_npi_min", [self], {"axis": axis,
                                            "keepdims": keepdims},
                       wrap=ndarray)

    def argmax(self, axis=None):
        return _invoke("_npi_argmax", [self], {"axis": axis}, wrap=ndarray)

    def argmin(self, axis=None):
        return _invoke("_npi_argmin", [self], {"axis": axis}, wrap=ndarray)

    def clip(self, min=None, max=None):
        return _invoke("_npi_clip", [self], {"a_min": min, "a_max": max},
                       wrap=ndarray)

    def squeeze(self, axis=None):
        return _invoke("_npi_squeeze", [self], {"axis": axis}, wrap=ndarray)

    def cumsum(self, axis=None, dtype=None):
        return _invoke("_npi_cumsum", [self],
                       {"axis": axis, "dtype": _npdt(dtype)}, wrap=ndarray)

    def round(self, decimals=0):
        return _invoke("_npi_round", [self], {"decimals": decimals},
                       wrap=ndarray)

    def dot(self, b):
        return self._bin(b, "_npi_dot")

    def copy(self):
        return ndarray(self._data)

    def any(self, axis=None, keepdims=False):
        return _invoke("_npi_any", [self], {"axis": axis,
                                            "keepdims": keepdims},
                       wrap=ndarray)

    def all(self, axis=None, keepdims=False):
        return _invoke("_npi_all", [self], {"axis": axis,
                                            "keepdims": keepdims},
                       wrap=ndarray)


def _npdt(dtype):
    """Canonicalize a dtype argument (None passes through)."""
    if dtype is None:
        return None
    return _onp.dtype(dtype).name


def _as_np(x, ctx=None):
    if isinstance(x, ndarray):
        return x
    if isinstance(x, NDArray):
        return ndarray(x._data)
    return array(x, ctx=ctx)


# ------------------------------------------------------------- creation ----

def array(object, dtype=None, ctx=None):
    """parity: multiarray.py array."""
    if isinstance(object, NDArray):
        object = object._data
    return ndarray(object, ctx=ctx or current_context(),
                   dtype=_npdt(dtype))


def zeros(shape, dtype=None, order="C", ctx=None):
    return array(_onp.zeros(shape if not isinstance(shape, int) else (shape,),
                            dtype=_npdt(dtype) or "float32"), ctx=ctx)


def ones(shape, dtype=None, order="C", ctx=None):
    return array(_onp.ones(shape if not isinstance(shape, int) else (shape,),
                           dtype=_npdt(dtype) or "float32"), ctx=ctx)


def full(shape, fill_value, dtype=None, order="C", ctx=None):
    return array(_onp.full(shape if not isinstance(shape, int) else (shape,),
                           fill_value, dtype=_npdt(dtype)), ctx=ctx)


def empty(shape, dtype=None, order="C", ctx=None):
    return zeros(shape, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return array(_onp.arange(start, stop, step, dtype=_npdt(dtype)), ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    out = _onp.linspace(start, stop, num, endpoint=endpoint,
                        retstep=retstep, dtype=_npdt(dtype), axis=axis)
    if retstep:
        return array(out[0], ctx=ctx), out[1]
    return array(out, ctx=ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None):
    return array(_onp.logspace(start, stop, num, endpoint=endpoint,
                               base=base, dtype=_npdt(dtype), axis=axis),
                 ctx=ctx)


def eye(N, M=None, k=0, dtype=None, ctx=None):
    return array(_onp.eye(N, M, k, dtype=_npdt(dtype) or "float32"), ctx=ctx)


def identity(n, dtype=None, ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def zeros_like(a, dtype=None):
    return _invoke_fn(lambda x: x * 0 if dtype is None
                      else (x * 0).astype(_npdt(dtype)),
                      "zeros_like", [_as_np(a)], {}, wrap=ndarray)


def ones_like(a, dtype=None):
    return _invoke_fn(lambda x: x * 0 + 1 if dtype is None
                      else (x * 0 + 1).astype(_npdt(dtype)),
                      "ones_like", [_as_np(a)], {}, wrap=ndarray)


def full_like(a, fill_value, dtype=None):
    return _invoke_fn(lambda x: x * 0 + fill_value if dtype is None
                      else (x * 0 + fill_value).astype(_npdt(dtype)),
                      "full_like", [_as_np(a)], {}, wrap=ndarray)


def empty_like(a, dtype=None):
    return zeros_like(a, dtype=dtype)


def copy(a):
    return _as_np(a).copy()


def ascontiguousarray(a, dtype=None):
    return _as_np(a) if dtype is None else _as_np(a).astype(dtype)


asarray = array


# ------------------------------------------------------------ dispatch -----

def _op_kw_names(op_name):
    """Keyword parameter names of an op's emitter, after the array arg —
    used to bind positional frontend args (np.tril(a, 1) -> k=1)."""
    import inspect

    from ..ops import registry as _reg

    params = list(inspect.signature(_reg.get(op_name).fn).parameters)
    return tuple(params[1:])


def _op1(op_name):
    """Single-tensor op wrapper: np.f(a, *args, **kwargs) with positional
    args bound onto the emitter's keyword parameters in order."""
    kw_names = None

    def f(a, *args, **kwargs):
        nonlocal kw_names
        a = _as_np(a)
        if args:
            if kw_names is None:
                kw_names = _op_kw_names(op_name)
            if len(args) > len(kw_names):
                raise TypeError(
                    f"{f.__name__}() takes at most {len(kw_names)} "
                    f"positional arguments after the array")
            kwargs.update(dict(zip(kw_names, args)))
        return _invoke(op_name, [a], kwargs, wrap=ndarray)

    f.__name__ = op_name.replace("_npi_", "")
    return f


def _op2(op_name, scalar_name=None):
    """Two-tensor op wrapper with scalar support."""

    def f(x1, x2, *a, **k):
        if isinstance(x1, NDArray):
            return _as_np(x1)._bin(x2, op_name, scalar_name)
        if isinstance(x2, NDArray):
            return _as_np(x2)._bin(x1, op_name, scalar_name, reverse=True)
        return f(array(x1), x2)

    f.__name__ = op_name.replace("_npi_", "")
    return f


# unary surface
for _n in ("negative", "reciprocal", "absolute", "sign", "rint", "ceil",
           "floor", "trunc", "fix", "square", "sqrt", "cbrt", "exp",
           "expm1", "log", "log10", "log2", "log1p", "sin", "cos", "tan",
           "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
           "arccosh", "arctanh", "degrees", "radians", "invert",
           "logical_not", "isnan", "isinf", "isposinf", "isneginf",
           "isfinite"):
    globals()[_n] = _op1(f"_npi_{_n}")
abs = absolute  # noqa: F821,A001

# binary surface
for _n in ("add", "subtract", "multiply", "true_divide", "floor_divide",
           "mod", "fmod", "remainder", "power", "maximum", "minimum",
           "fmax", "fmin", "hypot", "arctan2", "copysign", "ldexp",
           "logaddexp", "bitwise_and", "bitwise_or", "bitwise_xor",
           "left_shift", "right_shift", "logical_and", "logical_or",
           "logical_xor", "equal", "not_equal", "less", "less_equal",
           "greater", "greater_equal", "matmul", "dot", "inner", "outer",
           "kron", "cross", "gcd", "lcm", "vdot"):
    _scalar = _n if _n in ("add", "subtract", "multiply", "true_divide",
                           "mod", "power", "floor_divide") else None
    globals()[_n] = _op2(f"_npi_{_n}", _scalar)
divide = true_divide  # noqa: F821


# reductions / shape / etc. with explicit signatures
def sum(a, axis=None, dtype=None, keepdims=False):  # noqa: A001
    return _as_np(a).sum(axis=axis, dtype=dtype, keepdims=keepdims)


def mean(a, axis=None, dtype=None, keepdims=False):
    return _as_np(a).mean(axis=axis, dtype=dtype, keepdims=keepdims)


def std(a, axis=None, ddof=0, keepdims=False):
    return _as_np(a).std(axis=axis, ddof=ddof, keepdims=keepdims)


def var(a, axis=None, ddof=0, keepdims=False):
    return _as_np(a).var(axis=axis, ddof=ddof, keepdims=keepdims)


def prod(a, axis=None, keepdims=False):
    return _as_np(a).prod(axis=axis, keepdims=keepdims)


def max(a, axis=None, keepdims=False):  # noqa: A001
    return _as_np(a).max(axis=axis, keepdims=keepdims)


def min(a, axis=None, keepdims=False):  # noqa: A001
    return _as_np(a).min(axis=axis, keepdims=keepdims)


amax, amin = max, min


def argmax(a, axis=None):
    return _as_np(a).argmax(axis=axis)


def argmin(a, axis=None):
    return _as_np(a).argmin(axis=axis)


def clip(a, a_min=None, a_max=None):
    return _as_np(a).clip(a_min, a_max)


def round(a, decimals=0):  # noqa: A001
    return _as_np(a).round(decimals)


around = round
for _n in ("cumsum", "cumprod", "nansum", "nanprod", "median", "ptp",
           "any", "all", "count_nonzero", "sort", "argsort", "unique",
           "ediff1d", "ravel", "fliplr", "flipud",
           "atleast_1d", "atleast_2d", "atleast_3d", "trace", "diag",
           "diagonal", "diagflat", "tril", "triu", "nan_to_num"):
    globals()[_n] = _op1(f"_npi_{_n}")


def reshape(a, newshape, order="C"):
    return _as_np(a).reshape(newshape)


def transpose(a, axes=None):
    return _invoke("_npi_transpose", [_as_np(a)], {"axes": axes},
                   wrap=ndarray)


def swapaxes(a, axis1, axis2):
    return _invoke("_npi_swapaxes", [_as_np(a)],
                   {"dim1": axis1, "dim2": axis2}, wrap=ndarray)


def moveaxis(a, source, destination):
    return _invoke("_npi_moveaxis", [_as_np(a)],
                   {"source": source, "destination": destination},
                   wrap=ndarray)


def expand_dims(a, axis):
    return _invoke("_npi_expand_dims", [_as_np(a)], {"axis": axis},
                   wrap=ndarray)


def squeeze(a, axis=None):
    return _as_np(a).squeeze(axis)


def broadcast_to(a, shape):
    return _invoke("_npi_broadcast_to", [_as_np(a)], {"shape": tuple(shape)},
                   wrap=ndarray)


def flip(a, axis=None):
    return _invoke("_npi_flip", [_as_np(a)], {"axis": axis}, wrap=ndarray)


def roll(a, shift, axis=None):
    return _invoke("_npi_roll", [_as_np(a)], {"shift": shift, "axis": axis},
                   wrap=ndarray)


def rot90(a, k=1, axes=(0, 1)):
    return _invoke("_npi_rot90", [_as_np(a)], {"k": k, "axes": tuple(axes)},
                   wrap=ndarray)


def tile(a, reps):
    return _invoke("_npi_tile", [_as_np(a)], {"reps": reps}, wrap=ndarray)


def repeat(a, repeats, axis=None):
    return _invoke("_npi_repeat", [_as_np(a)],
                   {"repeats": repeats, "axis": axis}, wrap=ndarray)


def pad(a, pad_width, mode="constant", constant_values=0):
    return _invoke("_npi_pad", [_as_np(a)],
                   {"pad_width": _freeze_pads(pad_width), "mode": mode,
                    "constant_values": constant_values}, wrap=ndarray)


def _freeze_pads(pw):
    if isinstance(pw, int):
        return pw
    return tuple(tuple(p) if isinstance(p, (list, tuple)) else p
                 for p in pw)


def concatenate(seq, axis=0, out=None):
    return _invoke("_npi_concatenate", [_as_np(a) for a in seq],
                   {"axis": axis}, wrap=ndarray)


def stack(arrays, axis=0, out=None):
    return _invoke("_npi_stack", [_as_np(a) for a in arrays],
                   {"axis": axis}, wrap=ndarray)


def vstack(tup):
    return _invoke("_npi_vstack", [_as_np(a) for a in tup], {}, wrap=ndarray)


def hstack(tup):
    return _invoke("_npi_hstack", [_as_np(a) for a in tup], {}, wrap=ndarray)


def dstack(tup):
    return _invoke("_npi_dstack", [_as_np(a) for a in tup], {}, wrap=ndarray)


def column_stack(tup):
    return _invoke("_npi_column_stack", [_as_np(a) for a in tup], {},
                   wrap=ndarray)


def split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    if isinstance(ios, (list, tuple)):
        ios = tuple(ios)
    out = _invoke("_npi_split", [_as_np(ary)],
                  {"indices_or_sections": ios, "axis": axis}, wrap=ndarray)
    return list(out) if isinstance(out, tuple) else [out]


def array_split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    if isinstance(ios, (list, tuple)):
        ios = tuple(ios)
    out = _invoke("_npi_array_split", [_as_np(ary)],
                  {"indices_or_sections": ios, "axis": axis}, wrap=ndarray)
    return list(out) if isinstance(out, tuple) else [out]


def hsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=1)


def vsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=0)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _invoke("_npi_where",
                   [_as_np(condition), _as_np(x), _as_np(y)], {},
                   wrap=ndarray)


def nonzero(a):
    """Returns a tuple of 1-D index arrays (NumPy contract)."""
    out = _invoke("_npi_nonzero", [_as_np(a)], {}, wrap=ndarray)
    return out if isinstance(out, tuple) else (out,)


def take(a, indices, axis=None, mode="clip"):
    return _invoke("_npi_take", [_as_np(a), _as_np(indices)],
                   {"axis": axis, "mode": mode}, wrap=ndarray)


def take_along_axis(a, indices, axis):
    return _invoke("_npi_take_along_axis", [_as_np(a), _as_np(indices)],
                   {"axis": axis}, wrap=ndarray)


def searchsorted(a, v, side="left"):
    return _invoke("_npi_searchsorted", [_as_np(a), _as_np(v)],
                   {"side": side}, wrap=ndarray)


def bincount(x, weights=None, minlength=0):
    args = [_as_np(x)]
    if weights is not None:
        args.append(_as_np(weights))
        return _invoke_fn(
            lambda a, w: __import__("jax.numpy", fromlist=["x"]).bincount(
                a, weights=w, minlength=minlength), "bincount", args, {},
            wrap=ndarray)
    return _invoke("_npi_bincount", args, {"minlength": minlength},
                   wrap=ndarray)


def histogram(a, bins=10, range=None):
    return _invoke("_npi_histogram", [_as_np(a)],
                   {"bins": bins, "range": range}, wrap=ndarray)


def interp(x, xp, fp):
    return _invoke("_npi_interp", [_as_np(x), _as_np(xp), _as_np(fp)], {},
                   wrap=ndarray)


def diff(a, n=1, axis=-1):
    return _invoke("_npi_diff", [_as_np(a)], {"n": n, "axis": axis},
                   wrap=ndarray)


def gradient(f, axis=None):
    out = _invoke("_npi_gradient_op", [_as_np(f)], {"axis": axis},
                  wrap=ndarray)
    return list(out) if isinstance(out, tuple) else out


def meshgrid(*xi, indexing="xy"):
    out = _invoke("_npi_meshgrid", [_as_np(x) for x in xi],
                  {"indexing": indexing}, wrap=ndarray)
    return list(out) if isinstance(out, tuple) else [out]


def einsum(subscripts, *operands):
    return _invoke("_npi_einsum", [_as_np(o) for o in operands],
                   {"subscripts": subscripts}, wrap=ndarray)


def tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(ax) if isinstance(ax, (list, tuple)) else ax
                     for ax in axes)
    return _invoke("_npi_tensordot", [_as_np(a), _as_np(b)],
                   {"axes": axes}, wrap=ndarray)


def quantile(a, q, axis=None, keepdims=False):
    return _invoke("_npi_quantile", [_as_np(a)],
                   {"q": q, "axis": axis, "keepdims": keepdims},
                   wrap=ndarray)


def percentile(a, q, axis=None, keepdims=False):
    return _invoke("_npi_percentile", [_as_np(a)],
                   {"q": q, "axis": axis, "keepdims": keepdims},
                   wrap=ndarray)


def average(a, axis=None, weights=None):
    if weights is not None:
        return _invoke_fn(
            lambda x, w: __import__("jax.numpy", fromlist=["x"]).average(
                x, axis=axis, weights=w), "average",
            [_as_np(a), _as_np(weights)], {}, wrap=ndarray)
    return _invoke("_npi_average", [_as_np(a)], {"axis": axis}, wrap=ndarray)


def maximum_sctype(t):
    return _onp.float64


def may_share_memory(a, b, max_work=None):
    return False  # jax arrays are immutable buffers


def shares_memory(a, b, max_work=None):
    return False


def result_type(*args):
    return _onp.result_type(*[
        _onp.dtype(a.dtype) if isinstance(a, NDArray) else a for a in args])


def isscalar(element):
    return _onp.isscalar(element)


def shape(a):
    return _as_np(a).shape


def ndim(a):
    return _as_np(a).ndim


def size(a, axis=None):
    if axis is None:
        return _as_np(a).size
    return _as_np(a).shape[axis]


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return bool(_onp.allclose(_as_np(a).asnumpy(), _as_np(b).asnumpy(),
                              rtol=rtol, atol=atol, equal_nan=equal_nan))


def array_equal(a1, a2):
    return bool(_onp.array_equal(_as_np(a1).asnumpy(),
                                 _as_np(a2).asnumpy()))


def isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return _invoke_fn(
        lambda x, y: __import__("jax.numpy", fromlist=["x"]).isclose(
            x, y, rtol=rtol, atol=atol, equal_nan=equal_nan), "isclose",
        [_as_np(a), _as_np(b)], {}, wrap=ndarray)


def dtype(d):  # noqa: A001
    return _onp.dtype(d)


from . import linalg  # noqa: E402,F401
from . import random  # noqa: E402,F401


# ----------------------------------------------------- np frontend tail ----
# parity: the remaining multiarray.py functions over the npi tail ops

def hanning(M, dtype=None, ctx=None):
    return _invoke("_npi_hanning", [], {"M": int(M)}, wrap=ndarray)


def hamming(M, dtype=None, ctx=None):
    return _invoke("_npi_hamming", [], {"M": int(M)}, wrap=ndarray)


def blackman(M, dtype=None, ctx=None):
    return _invoke("_npi_blackman", [], {"M": int(M)}, wrap=ndarray)


def polyval(p, x):
    return _invoke("_npi_polyval", [_as_np(p), _as_np(x)], {}, wrap=ndarray)


def ediff1d(ary, to_end=None, to_begin=None):
    kw = {}
    if to_end is not None:
        kw["to_end"] = float(to_end)
    if to_begin is not None:
        kw["to_begin"] = float(to_begin)
    return _invoke("_npi_ediff1d", [_as_np(ary)], kw, wrap=ndarray)


def delete(arr, obj, axis=None):
    if isinstance(obj, slice):
        return _invoke("_npi_delete", [_as_np(arr)],
                       {"start": obj.start, "stop": obj.stop,
                        "step": obj.step, "axis": axis}, wrap=ndarray)
    if isinstance(obj, (int, _onp.integer)):
        return _invoke("_npi_delete", [_as_np(arr)],
                       {"obj": int(obj), "axis": axis}, wrap=ndarray)
    return _invoke_fn(
        lambda a, o: __import__("jax").numpy.asarray(
            _onp.delete(_onp.asarray(a), _onp.asarray(o), axis=axis)),
        "_npi_delete", [_as_np(arr), _as_np(obj)], {}, wrap=ndarray)


def insert(arr, obj, values, axis=None):
    if isinstance(obj, slice):
        return _invoke("_npi_insert_slice", [_as_np(arr), _as_np(values)],
                       {"start": obj.start, "stop": obj.stop,
                        "step": obj.step, "axis": axis}, wrap=ndarray)
    if isinstance(obj, (int, _onp.integer)):
        return _invoke("_npi_insert_scalar", [_as_np(arr)],
                       {"obj": int(obj), "val": values, "axis": axis},
                       wrap=ndarray) if _onp.isscalar(values) else \
            _invoke_fn(
                lambda a, v: __import__("jax").numpy.asarray(
                    _onp.insert(_onp.asarray(a), int(obj),
                                _onp.asarray(v), axis=axis)),
                "_npi_insert", [_as_np(arr), _as_np(values)], {},
                wrap=ndarray)
    return _invoke("_npi_insert_tensor",
                   [_as_np(arr), _as_np(obj), _as_np(values)],
                   {"axis": axis}, wrap=ndarray)


def diag_indices_from(arr):
    return _invoke("_npi_diag_indices_from", [_as_np(arr)], {},
                   wrap=ndarray)


def dsplit(ary, indices_or_sections):
    return split(ary, indices_or_sections, axis=2)


def deg2rad(x):
    return _invoke("_npi_deg2rad", [_as_np(x)], {}, wrap=ndarray)


def rad2deg(x):
    return _invoke("_npi_rad2deg", [_as_np(x)], {}, wrap=ndarray)


def bitwise_not(x):
    return _invoke("_npi_bitwise_not", [_as_np(x)], {}, wrap=ndarray)


def around(x, decimals=0):
    if decimals:
        scale = 10.0 ** decimals
        return _invoke_fn(
            lambda a: __import__("jax").numpy.round(a * scale) / scale,
            "around", [_as_np(x)], {}, wrap=ndarray)
    return _invoke("_npi_around", [_as_np(x)], {}, wrap=ndarray)


round_ = around
