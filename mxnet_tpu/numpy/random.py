"""mx.np.random (parity: `python/mxnet/numpy/random.py` over
`src/operator/numpy/random/`). Draws from the framework's stateful
seed->key stream (`mxnet_tpu.random`), so `mx.random.seed` governs these
samplers too, and inside a CachedOp trace the key is threaded through the
executable like every other random op."""
from __future__ import annotations

from .. import random as _framework_random
from ..ndarray.ndarray import _invoke
from . import _as_np, ndarray  # noqa: F401

__all__ = ["seed", "uniform", "normal", "randint", "rand", "randn",
           "choice", "shuffle", "permutation", "gamma", "exponential",
           "beta", "poisson", "multinomial", "bernoulli", "pareto", "weibull", "rayleigh"]


def seed(seed_value):
    _framework_random.seed(seed_value)


def _size(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_uniform", [],
                   {"low": low, "high": high, "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def normal(loc=0.0, scale=1.0, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_normal", [],
                   {"loc": loc, "scale": scale,
                    "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def randint(low, high=None, size=None, dtype="int32", ctx=None):
    if high is None:
        low, high = 0, low
    return _invoke("_npi_random_randint", [],
                   {"low": low, "high": high,
                    "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def rand(*size):
    return uniform(size=size or ())


def randn(*size):
    return normal(size=size or ())


def choice(a, size=None, replace=True, p=None):
    if isinstance(a, int):
        from . import arange

        a = arange(a)
    args = [_as_np(a)]
    kwargs = {"key": _framework_random.next_key(), "size": _size(size),
              "replace": replace}
    if p is not None:
        import jax

        from ..ndarray.ndarray import _invoke_fn

        return _invoke_fn(
            lambda arr, probs: jax.random.choice(
                kwargs["key"], arr, shape=kwargs["size"], replace=replace,
                p=probs), "choice", [args[0], _as_np(p)], {}, wrap=ndarray)
    return _invoke("_npi_random_choice", args, kwargs, wrap=ndarray)


def permutation(x):
    if isinstance(x, int):
        from . import arange

        x = arange(x)
    return _invoke("_npi_random_permutation", [_as_np(x)],
                   {"key": _framework_random.next_key()}, wrap=ndarray)


def shuffle(x):
    """In-place shuffle along the first axis (parity: np.random.shuffle)."""
    out = permutation(x)
    x._rebind(out._data)


def gamma(shape, scale=1.0, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_gamma", [],
                   {"shape_param": shape, "scale": scale,
                    "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def exponential(scale=1.0, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_exponential", [],
                   {"scale": scale, "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def beta(a, b, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_beta", [],
                   {"a": a, "b": b, "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def poisson(lam=1.0, size=None, dtype="int32", ctx=None):
    return _invoke("_npi_random_poisson", [],
                   {"lam": lam, "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def bernoulli(p=0.5, size=None, dtype="float32", ctx=None):
    return _invoke("_npi_random_bernoulli", [],
                   {"p": p, "key": _framework_random.next_key(),
                    "size": _size(size), "dtype": dtype}, wrap=ndarray)


def multinomial(n, pvals, size=None):
    """Sample counts from a multinomial (parity: np.random.multinomial)."""
    import jax

    from ..ndarray.ndarray import _invoke_fn

    key = _framework_random.next_key()
    shape = _size(size)

    def _mn(p):
        import jax.numpy as jnp

        draws = jax.random.categorical(
            key, jnp.log(jnp.maximum(p, 1e-30)), shape=shape + (n,))
        return jax.nn.one_hot(draws, p.shape[-1]).sum(axis=-2) \
            .astype(jnp.int32)

    return _invoke_fn(_mn, "multinomial", [_as_np(pvals)], {}, wrap=ndarray)


def pareto(a=1.0, size=None):
    return _invoke("_npi_pareto", [],
                   {"a": float(a), "key": _framework_random.next_key(),
                    "size": _size(size)}, wrap=ndarray)


def weibull(a=1.0, size=None):
    return _invoke("_npi_weibull", [],
                   {"a": float(a), "key": _framework_random.next_key(),
                    "size": _size(size)}, wrap=ndarray)


def rayleigh(scale=1.0, size=None):
    return _invoke("_npi_rayleigh", [],
                   {"scale": float(scale),
                    "key": _framework_random.next_key(),
                    "size": _size(size)}, wrap=ndarray)
