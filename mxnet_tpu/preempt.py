"""Graceful preemption drain: SIGTERM -> finish the step, checkpoint, exit.

Preemptible TPU fleets deliver SIGTERM with a short grace window (~30s)
before pulling the machine. Without a handler the process dies mid-step
with nothing written; with this module the run *drains*:

1. :func:`install` hooks SIGTERM/SIGINT. The handler only sets a **drain
   flag** and records the event — nothing is interrupted, so the in-flight
   step always finishes (a second signal skips the grace and exits
   immediately with the reschedule code).
2. The training loops check :func:`requested` after every step/batch —
   ``ShardedTrainer.step`` raises :class:`DrainRequested` rather than
   start a NEW step once the flag is up, and the estimator/module fit
   loops drain themselves. The predict server
   (``serving.ModelServer.run_until_drained``) polls the same flag: it
   stops admission, answers every admitted request, then exits 75.
3. :func:`drain` writes the final checkpoint (an explicit ``save``
   callable, or the hook installed with ``watchdog.set_last_resort`` —
   ``ShardedTrainer.save_checkpoint``/``resume`` register one
   automatically), records a **drain event** JSON next to the crash
   bundles, and exits with :func:`exit_code` (default 75, ``EX_TEMPFAIL``)
   so gang supervisors / wrappers know to *reschedule*, not fail the job.

Exit codes — the **ladder** gang supervisors (``mxnet_tpu.elastic``,
``tools/launch.py --supervise``) and wrappers dispatch on::

    75   graceful preemption drain (this module; reschedule + resume)
    76   peer lost (EX_PROTOCOL) — a kvstore collective raised
         PeerLostError and nobody recovered; the gang excepthook
         (elastic.install_excepthook) maps it onto the process exit code
    86   watchdog stall abort (mxnet_tpu.watchdog.ABORT_EXIT_CODE)
    137  SIGKILL — a hard preemption with no grace; resume from the last
         periodic checkpoint (CheckpointManager falls back past torn files)

:data:`EXIT_LADDER` names them; :func:`classify_exit` and
:func:`most_severe` are the shared decision helpers (``tools/launch.py``
keeps an import-light copy of the severity table — keep them in sync).

Environment knobs (all optional; see ``tools/diagnose.py``)::

    MXNET_TPU_PREEMPT            auto-install at first trainer/fit use:
                                 "1" (SIGTERM+SIGINT), "sigterm",
                                 "sigterm,sigint"; "0"/unset = manual
    MXNET_TPU_PREEMPT_EXIT_CODE  drain exit code (default 75)
    MXNET_TPU_PREEMPT_DIR        drain-event directory (default: the
                                 watchdog crash dir)
    MXNET_TPU_PREEMPT_RESHARD    "0" forbids resuming a checkpoint on a
                                 different topology (ShardedTrainer.resume)

Every path is deterministically testable: the ``preempt`` fault mode
(:mod:`mxnet_tpu.faults`) delivers SIGTERM to the process at a named
injection point, e.g. ``MXNET_TPU_FAULTS="trainer.step:preempt@6"``.
"""
from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time

from . import log as _log
from .telemetry import flight as _flight

__all__ = ["DrainRequested", "DRAIN_EXIT_CODE", "PEERLOST_EXIT_CODE",
           "EXIT_LADDER", "canonical_exit", "classify_exit",
           "exit_severity", "most_severe", "install", "installed",
           "uninstall", "maybe_install_from_env", "requested", "request",
           "clear", "event", "drain", "exit_code", "drain_dir",
           "last_drain", "describe"]

DRAIN_EXIT_CODE = 75     # EX_TEMPFAIL: transient failure, please reschedule
PEERLOST_EXIT_CODE = 76  # EX_PROTOCOL: a gang peer died under a collective

#: the exit-code ladder, least to most severe; anything unlisted is an
#: "error" — a real bug, NOT a reschedule
EXIT_LADDER = {0: "ok", DRAIN_EXIT_CODE: "drain",
               PEERLOST_EXIT_CODE: "peer-lost", 86: "watchdog-abort",
               137: "killed"}

# severity: ok < drain < peer-lost < watchdog-abort < killed < error
_SEVERITY = {code: i for i, code in enumerate(EXIT_LADDER)}
_UNKNOWN_SEVERITY = len(EXIT_LADDER)


def canonical_exit(rc):
    """Normalise a ``Popen.returncode`` (negative = killed by signal N)
    to the shell convention ``128 + N`` (so SIGKILL is always 137)."""
    if rc is None:
        return None
    rc = int(rc)
    return 128 - rc if rc < 0 else rc


def classify_exit(rc) -> str:
    """The ladder name for an exit code: ``ok`` / ``drain`` /
    ``peer-lost`` / ``watchdog-abort`` / ``killed`` / ``error``."""
    return EXIT_LADDER.get(canonical_exit(rc), "error")


def exit_severity(rc) -> int:
    """Ladder position (higher = worse); unknown codes rank worst."""
    return _SEVERITY.get(canonical_exit(rc), _UNKNOWN_SEVERITY)


def most_severe(codes):
    """The most severe exit code of an iterable (0 when empty) — what a
    launcher should propagate for a gang, instead of whichever child it
    happened to ``wait()`` on last."""
    best, best_sev = 0, -1
    for rc in codes:
        rc = canonical_exit(rc)
        if rc is None:
            continue
        sev = _SEVERITY.get(rc, _UNKNOWN_SEVERITY)
        if sev > best_sev:
            best, best_sev = rc, sev
    return best

_logger = _log.get_logger("mxnet_tpu.preempt")

_lock = threading.Lock()
_installed: dict[int, object] = {}   # signum -> previous handler
_requested = False
_event: dict | None = None
_exit_fn = os._exit  # test seam for the second-signal fast path

_SIGNALS = {"sigterm": _signal.SIGTERM, "sigint": _signal.SIGINT}


class DrainRequested(RuntimeError):
    """A preemption drain is pending: no new step may start.

    Raised by ``ShardedTrainer.step`` when the drain flag is up — the
    *previous* step has completed, so the caller should write its final
    checkpoint (or just call :func:`drain`) and exit for reschedule.
    """

    def __init__(self, ev=None):
        self.event = dict(ev or {})
        why = self.event.get("signal") or self.event.get("reason") or "?"
        super().__init__(
            f"preemption drain requested ({why}); finish up, write a "
            "final checkpoint (preempt.drain()) and exit for reschedule")


def exit_code() -> int:
    """The drain exit code (MXNET_TPU_PREEMPT_EXIT_CODE, default 75)."""
    try:
        return int(os.environ.get("MXNET_TPU_PREEMPT_EXIT_CODE",
                                  DRAIN_EXIT_CODE))
    except ValueError:
        return DRAIN_EXIT_CODE


def _signal_name(signum):
    try:
        return _signal.Signals(signum).name
    except ValueError:
        return f"signal {signum}"


def _handler(signum, frame):
    global _requested
    if _requested:
        # the platform is out of patience (second delivery): exit NOW with
        # the reschedule code — a half-written checkpoint is protected by
        # the manager's atomic writes
        _logger.error("preempt: second %s during drain; exiting %d "
                      "immediately", _signal_name(signum), exit_code())
        _exit_fn(exit_code())
        return  # only reachable through the test seam
    _requested = True
    globals()["_event"] = {"signal": _signal_name(signum),
                           "signum": int(signum),
                           "t_wall": time.time(),
                           "t_mono": time.monotonic(),
                           "pid": os.getpid()}
    _flight.rec("preempt.request", "signal", _signal_name(signum))
    _logger.warning(
        "preempt: received %s — draining (the in-flight step finishes, "
        "then a final checkpoint is written and the process exits %d)",
        _signal_name(signum), exit_code())


def _parse_signals(spec):
    if spec is None or spec.strip() in ("", "1", "true", "yes"):
        return (_signal.SIGTERM, _signal.SIGINT)
    out = []
    for tok in spec.replace(";", ",").split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        if tok not in _SIGNALS:
            raise ValueError(f"unknown MXNET_TPU_PREEMPT signal {tok!r}; "
                             f"expected one of {sorted(_SIGNALS)}")
        out.append(_SIGNALS[tok])
    return tuple(out) or (_signal.SIGTERM, _signal.SIGINT)


def install(signals=None):
    """Install the drain handlers (idempotent; main thread only).

    signals : iterable of signal numbers, or None for SIGTERM+SIGINT.
    Returns True when handlers are (now) installed, False when running on
    a non-main thread where Python forbids signal.signal.
    """
    if signals is None:
        signals = (_signal.SIGTERM, _signal.SIGINT)
    with _lock:
        try:
            for s in signals:
                if s not in _installed:
                    _installed[s] = _signal.signal(s, _handler)
        except ValueError:
            # signal.signal outside the main thread; the caller keeps
            # polling requested(), which a main-thread install would set
            _logger.warning("preempt: cannot install signal handlers "
                            "outside the main thread")
            return False
    return True


def installed() -> bool:
    return bool(_installed)


def uninstall():
    """Restore the previous handlers and clear the drain state (tests)."""
    with _lock:
        for s, prev in list(_installed.items()):
            try:
                _signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        _installed.clear()
    clear()


def maybe_install_from_env():
    """Install per ``MXNET_TPU_PREEMPT`` (no-op when unset/"0"). Called by
    ShardedTrainer and the estimator/module fit loops so one env var arms
    the whole stack; explicit :func:`install` always works too."""
    spec = os.environ.get("MXNET_TPU_PREEMPT", "")
    if not spec or spec.strip() in ("0", "false", "no"):
        return False
    if _installed:
        return True
    try:
        return install(_parse_signals(spec))
    except ValueError as e:
        _logger.warning("ignoring invalid MXNET_TPU_PREEMPT: %s", e)
        return False


def requested() -> bool:
    """True once a drain has been requested (signal or :func:`request`).
    Cheap — one module-global read — so loops can poll it per batch."""
    return _requested


def request(reason="api"):
    """Programmatic drain request (same flag the signal handler sets)."""
    global _requested
    with _lock:
        if not _requested:
            _requested = True
            globals()["_event"] = {"reason": str(reason),
                                   "t_wall": time.time(),
                                   "t_mono": time.monotonic(),
                                   "pid": os.getpid()}
            _flight.rec("preempt.request", "api", str(reason))
    return _event


def clear():
    """Reset the drain flag/event (after a handled drain, or in tests)."""
    global _requested, _event
    with _lock:
        _requested = False
        _event = None


def event():
    """The pending drain-request event dict, or None."""
    return dict(_event) if _event else None


# ------------------------------------------------------------- the drain ---

def drain_dir():
    """Where drain-event records go: MXNET_TPU_PREEMPT_DIR, else the
    watchdog crash dir (one place to look after any kind of death)."""
    d = os.environ.get("MXNET_TPU_PREEMPT_DIR")
    if d:
        return d
    from . import watchdog as _watchdog

    return _watchdog.crash_dir()


def _write_event(ev, directory=None):
    from .checkpoint import atomic_write

    root = directory or drain_dir()
    try:
        os.makedirs(root, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(root, f"drain-{stamp}-p{os.getpid()}.json")
        payload = json.dumps(ev, indent=1, sort_keys=True, default=repr)

        def writer(tmp):
            with open(tmp, "w") as f:
                f.write(payload)

        atomic_write(path, writer)
        return path
    except OSError as e:
        _logger.error("preempt: failed to record drain event: %s", e)
        return None


def last_drain(directory=None):
    """Parse the newest ``drain-*.json`` record (diagnose.py), or None."""
    root = directory or drain_dir()
    try:
        cands = [os.path.join(root, n) for n in os.listdir(root)
                 if n.startswith("drain-") and n.endswith(".json")]
    except OSError:
        return None
    for path in sorted(cands, key=os.path.getmtime, reverse=True):
        try:
            with open(path) as f:
                ev = json.load(f)
            ev["path"] = path
            return ev
        except (OSError, ValueError):
            continue
    return None


def drain(save=None, exit=True, code=None, directory=None):
    """The drain terminal: final checkpoint + drain record + exit.

    save : callable writing the final checkpoint; None uses the hook
        installed with ``watchdog.set_last_resort`` (ShardedTrainer
        registers one on every ``save_checkpoint``/``resume``); False
        skips the save (the caller already checkpointed).
    exit : raise ``SystemExit(code)`` after recording (the graceful twin
        of the watchdog's ``os._exit(86)`` — atexit and buffers flush).
        Pass False to keep running (in-process drills, tests).
    Returns the drain-event dict (when ``exit=False``).
    """
    ev = event() or {"reason": "drain() without a pending request",
                     "t_wall": time.time(), "pid": os.getpid()}
    ev["exit_code"] = int(code if code is not None else exit_code())
    if os.environ.get("MXTPU_GANG_DIR"):
        # supervised run: the gang coordinates make the drain record
        # attributable in the supervisor's post-mortem
        ev["gang"] = {"dir": os.environ["MXTPU_GANG_DIR"],
                      "rank": os.environ.get("MXTPU_WORKER_ID"),
                      "generation": os.environ.get("MXTPU_GANG_GENERATION")}
    hook = save
    if hook is None:
        from . import watchdog as _watchdog

        hook = _watchdog.last_resort()
    if hook is False or hook is None:
        ev["final_checkpoint"] = ("skipped" if hook is False else
                                  "no hook installed")
    else:
        try:
            result = hook()
            ev["final_checkpoint"] = "written"
            if isinstance(result, dict):  # manager {name: path} map
                ev["checkpoint_files"] = {k: str(v)
                                          for k, v in result.items()}
        except Exception as e:  # a failed save must not mask the drain
            _logger.error("preempt: final checkpoint failed: %s", e)
            ev["final_checkpoint"] = f"failed: {type(e).__name__}: {e}"
    # the flight-recorder tail rides in every drain record: what the
    # process was doing when the platform pulled the plug, with no
    # profiler session required
    _flight.rec("preempt.drain", "drain",
                ev.get("signal") or ev.get("reason"))
    ev["flight_tail"] = _flight.tail(64)
    ev["recorded"] = _write_event(ev, directory)
    try:
        # flush drain evidence to the gang heartbeat NOW: exiting faster
        # than the daemon's cadence must not cost the on-disk "draining"
        # state a restarted supervisor classifies orphan exits from
        from . import elastic as _elastic
        _elastic.final_beat()
    except Exception:
        pass
    _logger.warning("preempt: drained (%s); final checkpoint: %s; "
                    "exiting %d for reschedule",
                    ev.get("signal") or ev.get("reason"),
                    ev["final_checkpoint"], ev["exit_code"])
    if exit:
        raise SystemExit(ev["exit_code"])
    return ev


def describe():
    """Effective knobs + state as a plain dict (diagnose.py)."""
    return {"installed": sorted(_signal_name(s) for s in _installed),
            "requested": _requested, "event": event(),
            "exit_code": exit_code(), "drain_dir": drain_dir(),
            "env": os.environ.get("MXNET_TPU_PREEMPT", "<unset>")}
