"""Device / Context model.

Parity target: ``Context`` in the reference (`include/mxnet/base.h:102-188`,
Python mirror `python/mxnet/context.py:28-311`): a (device_type, device_id)
pair used to place NDArrays and route work to per-device execution lanes.

TPU-native redesign: a Context wraps a ``jax.Device``. Device types are
``cpu`` and ``tpu`` (``kCPU=1``/``kTPU=2`` — the reference's ``kGPU`` slot is
taken by the TPU). ``cpu_pinned`` maps to plain host memory (PJRT manages
pinned staging buffers itself), and ``cpu_shared`` (DataLoader IPC) maps to
host shared memory handled at the Python layer.

Placement itself is delegated to XLA: a Context resolves to a concrete
``jax.Device`` (or, for sharded arrays, a `mxnet_tpu.parallel` mesh), and the
runtime uses ``jax.device_put`` / sharding constraints instead of explicit
stream assignment.
"""
from __future__ import annotations

import threading
from typing import Optional

__all__ = [
    "Context",
    "cpu",
    "tpu",
    "gpu",
    "cpu_pinned",
    "num_tpus",
    "num_gpus",
    "current_context",
    "default_context",
]


class Context:
    """A device context (device_type, device_id).

    Acts as a context manager exactly like the reference's
    ``with mx.tpu(0):`` idiom, setting the thread-local default device.
    """

    # parity: include/mxnet/base.h:105-110 (kCPU=1, kGPU=2, kCPUPinned=3, kCPUShared=5)
    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "gpu": 2}

    _tls = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise ValueError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __enter__(self):
        if not hasattr(Context._tls, "stack"):
            Context._tls.stack = []
        Context._tls.stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        Context._tls.stack.pop()

    # -- JAX resolution -----------------------------------------------------
    def jax_device(self):
        """Resolve this Context to a concrete jax.Device.

        ``tpu`` falls back to the first accelerator (or CPU on CPU-only
        hosts) so test suites written against ``mx.tpu()`` run anywhere —
        the same trick the reference uses with ``default_context()``
        (`python/mxnet/test_utils.py:58`).
        """
        import jax

        # LOCAL devices only: under jax.distributed the global list
        # contains other processes' (non-addressable) devices
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            # the CPU backend always exists, even on accelerator hosts
            devs = jax.local_devices(backend="cpu")
            return devs[self.device_id % len(devs)]
        # tpu: prefer real TPU devices, else whatever the default backend is
        devs = [d for d in jax.local_devices()
                if d.platform in ("tpu", "axon")]
        if not devs:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


# Compatibility alias: reference code says mx.gpu(); on this framework the
# accelerator is a TPU.
def gpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_tpus() -> int:
    """Count attached accelerator devices. Counts any non-CPU platform
    (real "tpu" as well as tunnel-attached platforms like "axon") so
    device selection matches `Context.jax_device`'s resolution — a bench
    host whose chip shows up under an experimental platform name must
    not silently fall back to CPU (parity: python/mxnet/context.py:246
    num_gpus)."""
    import jax

    try:
        n = len([d for d in jax.local_devices()
                 if d.platform not in ("cpu",)])
    except RuntimeError:
        return 0
    if n:
        return n
    # default backend is CPU (e.g. JAX_PLATFORMS="cpu,tpu" priority):
    # an explicit tpu backend may still exist alongside it
    try:
        return len(jax.local_devices(backend="tpu"))
    except RuntimeError:
        return 0


def num_gpus() -> int:  # parity alias (python/mxnet/context.py:246)
    return num_tpus()


def current_context() -> Context:
    """The active default context (thread-local `with ctx:` stack)."""
    stack = getattr(Context._tls, "stack", None)
    if stack:
        return stack[-1]
    return Context._default_ctx


def default_context() -> Context:
    return current_context()


Context._default_ctx = Context("cpu", 0)
