"""Monitor: tensor statistics for debugging (parity: `python/mxnet/monitor.py:32`).

The reference taps every engine op's outputs via a C callback installed on
the executor (`set_monitor_callback`). On TPU the bound graph is ONE XLA
executable, so per-op intermediates are fused away; the monitor therefore
reports what is observable at the executable boundary — arguments,
auxiliary states, gradients, and outputs — which covers the reference's
dominant use (weight/grad/output health checks). Pattern filtering, custom
`stat_func`, `tic`/`toc`/`toc_print` all match the reference protocol.
"""
from __future__ import annotations

import logging
import math
import re

__all__ = ["Monitor"]


class Monitor:
    """parity: monitor.py:32."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                """|x| mean surrogate: norm(x)/sqrt(size) (reference default)."""
                return x.norm() / math.sqrt(x.size)

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

    def install(self, exe):
        """Attach to an Executor (parity: monitor.py install)."""
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch; call before forward."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def _collect(self, exe):
        sym = exe._symbol
        seen = set()

        def emit(name, arr):
            if arr is None or id(arr) in seen:
                return
            seen.add(id(arr))
            if self.re_prog.match(name):
                self.queue.append((self.step, name, self.stat_func(arr)))

        for name, arr in zip(sym.list_arguments(), exe.arg_arrays):
            emit(name, arr)
            grad = exe.grad_dict.get(name)
            if grad is not None:
                emit(name + "_grad", grad)
        for name, arr in zip(sym.list_auxiliary_states(), exe.aux_arrays):
            emit(name, arr)
        for name, arr in zip(sym.list_outputs(), exe.outputs or []):
            emit(name, arr)

    def toc(self):
        """Finish collecting; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        for exe in self.exes:
            self._collect(exe)
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            from .ndarray import NDArray

            if isinstance(v_list, NDArray):
                v_list = [v_list]
            s = ""
            for v in v_list:
                if v.size == 1:
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """parity: monitor.py:141."""
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
