"""Unified telemetry: the one observability seam of the framework.

Every subsystem built so far grew its own instrumentation island — the
profiler's Chrome-trace events, watchdog crash bundles, serving
``stats()``, ``compile_cache.*`` counters. This package is the seam that
ties them together for a fleet operator:

* :mod:`~mxnet_tpu.telemetry.registry` — counters/gauges/histograms with
  bounded label sets, fed by push (coarse events) and pull (collectors
  over the subsystems' existing counters), exported as Prometheus text +
  JSON;
* :mod:`~mxnet_tpu.telemetry.export` — the collectors, the standalone
  :class:`~mxnet_tpu.telemetry.export.MetricsServer`, and the rendering
  behind the serving front end's ``GET /metrics``;
* :mod:`~mxnet_tpu.telemetry.flight` — the always-on constant-memory
  flight recorder whose tail ships in every watchdog crash bundle and
  preemption drain event;
* :mod:`~mxnet_tpu.telemetry.memory` — device-memory live/peak gauges
  (allocator stats, ``live_arrays`` fallback) + OOM forensics over the
  per-executable ``memory_analysis()`` captured at compile time;
* :mod:`~mxnet_tpu.telemetry.costs` — per-executable
  ``cost_analysis()`` records, the per-device-kind peak-TFLOPS table,
  and the measured ``mfu_xla`` arithmetic;
* :mod:`~mxnet_tpu.telemetry.steps` — the per-step phase timeline
  (data-wait / h2d / compute / optimizer / sync);
* :mod:`~mxnet_tpu.telemetry.trace` — the span tracer: propagated
  request ids through the serving pipeline (five-phase per-request
  breakdowns), trainer-step spans keyed (generation, rank, step), and
  the merged multi-rank Perfetto ``trace.json`` exporter;
* :mod:`~mxnet_tpu.telemetry.fleet` — per-rank telemetry shards next to
  the gang heartbeat files, fleet-level ``mxtpu_fleet_*`` aggregation on
  one scrape endpoint, and the ``mxtpu_gang_straggler_*`` skew/straggler
  verdict.

Knobs: ``MXNET_TPU_TELEMETRY=0`` disables push instrumentation
(:func:`set_enabled` at runtime); ``MXNET_TPU_FLIGHT`` sizes the flight
ring; ``MXNET_TPU_TELEMETRY_MEMSAMPLE`` paces step-boundary memory
samples; ``MXNET_TPU_TELEMETRY_XCOST`` scopes executable-analysis
capture; ``MXNET_TPU_TELEMETRY_MAX_SERIES`` bounds per-metric
cardinality. Overhead contract: disabled, every hook is one
module-global check; enabled, nothing runs on the per-op dispatch path
(the A/B perf gate in ``tests/test_telemetry.py`` holds ``opperf
--dispatch`` within noise). See ``docs/OBSERVABILITY.md``.
"""
from __future__ import annotations

from . import (_state, costs, export, fleet, flight, memory, registry,
               steps, trace)
from ._state import set_enabled
from .export import (MetricsServer, metrics_snapshot, register_collector,
                     render_prometheus)

__all__ = ["enabled", "set_enabled", "describe", "registry", "flight",
           "costs", "memory", "steps", "export", "trace", "fleet",
           "MetricsServer", "metrics_snapshot", "render_prometheus",
           "register_collector"]


def enabled() -> bool:
    """True when push instrumentation is active."""
    return _state.enabled


def describe():
    """Effective knobs + state as a plain dict (``tools/diagnose.py``)."""
    import os

    return {
        "enabled": _state.enabled,
        "env": os.environ.get("MXNET_TPU_TELEMETRY", "<unset>"),
        "flight_ring": flight.size(),
        "flight_events": sum(flight.counts().values()),
        "metrics": len(registry.all_metrics()),
        "memory_sample_every": memory.sample_every(),
        "executables_tracked": {s: a["executables"]
                                for s, a in costs.aggregate().items()},
        "last_step": steps.last(),
        "trace": trace.describe(),
    }
