"""Metrics export: subsystem collectors + the ``/metrics`` endpoints.

The scrape path: :func:`collect` runs every registered collector —
pull-based adapters that copy counters the subsystems already keep
(``compile.stats()``, ``serving.live_stats()``, the watchdog stall
count, kvstore op counts, device memory, flight-recorder totals) into
the :mod:`~mxnet_tpu.telemetry.registry` — then the registry renders
Prometheus text (:func:`render_prometheus`) or JSON
(:func:`metrics_snapshot`).

Collectors look subsystems up through ``sys.modules``: a module that was
never imported has no traffic to report, and a scrape must never be the
thing that pulls jax (or the serving stack) into a process.

Serving exposure:

* the serving :class:`~mxnet_tpu.serving.http.HttpFrontEnd` mounts
  ``GET /metrics`` (Prometheus text) and ``GET /metrics.json`` directly
  — one port serves predictions and observability;
* :class:`MetricsServer` is the standalone twin for processes without a
  serving front end (trainers): ``MetricsServer(port=9100).start()``
  exposes ``/metrics``, ``/metrics.json`` and ``/healthz``.
"""
from __future__ import annotations

import json
import sys
import threading

from . import costs as _costs, flight as _flight, memory as _memory
from . import registry as _registry
from . import trace as _trace

__all__ = ["register_collector", "unregister_collector", "collect",
           "metrics_snapshot", "render_prometheus", "render_json",
           "MetricsServer", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_lock = threading.Lock()
_COLLECTORS = []           # (name, fn)
_defaults_installed = False


def register_collector(name, fn):
    """Register a scrape-time collector (replaces a previous one of the
    same name)."""
    with _lock:
        for i, (n, _) in enumerate(_COLLECTORS):
            if n == name:
                _COLLECTORS[i] = (name, fn)
                return
        _COLLECTORS.append((name, fn))


def unregister_collector(name):
    """Remove the collector registered as `name` (tests / fleet
    teardown). Returns True when one was removed."""
    with _lock:
        for i, (n, _) in enumerate(_COLLECTORS):
            if n == name:
                del _COLLECTORS[i]
                return True
    return False


def collect():
    """Run every collector (errors are swallowed per collector — one
    broken subsystem must not take down the whole scrape). Returns the
    list of collector names that raised."""
    _ensure_defaults()
    errors = []
    with _lock:
        items = list(_COLLECTORS)
    for name, fn in items:
        try:
            fn()
        except Exception:
            errors.append(name)
    if errors:
        _registry.gauge("mxtpu_collector_errors",
                        "Collectors that raised at the last scrape").set(
                            len(errors))
    return errors


def metrics_snapshot():
    """Collect, then return the registry as a JSON-able dict."""
    collect()
    return _registry.snapshot()


def render_prometheus():
    """Collect, then render the registry in Prometheus text format."""
    collect()
    return _registry.render_prometheus()


def render_json():
    return json.dumps(metrics_snapshot(), sort_keys=True)


# ---------------------------------------------------- default collectors ---

def _collect_compile():
    mod = sys.modules.get("mxnet_tpu.compile")
    if mod is None:
        return
    hits = _registry.counter("mxtpu_compile_cache_hits_total",
                             "Compile-service in-memory cache hits",
                             labels=("site",))
    misses = _registry.counter("mxtpu_compile_cache_misses_total",
                               "Compile-service cache misses",
                               labels=("site",))
    disk = _registry.counter("mxtpu_compile_cache_disk_hits_total",
                             "Compile-service persistent-cache hits",
                             labels=("site",))
    compiles = _registry.counter("mxtpu_compile_compiles_total",
                                 "Fresh XLA compiles", labels=("site",))
    cms = _registry.counter("mxtpu_compile_ms_total",
                            "Milliseconds spent compiling",
                            labels=("site",))
    lms = _registry.counter("mxtpu_compile_load_ms_total",
                            "Milliseconds spent loading cached "
                            "executables", labels=("site",))
    for site, st in mod.stats().items():
        hits.set_total(st["hits"], site)
        misses.set_total(st["misses"], site)
        disk.set_total(st["disk_hits"], site)
        compiles.set_total(st["compiles"], site)
        cms.set_total(st["compile_ms"], site)
        lms.set_total(st["load_ms"], site)


def _collect_serving():
    mod = sys.modules.get("mxnet_tpu.serving.server")
    if mod is None:
        return
    req = _registry.counter("mxtpu_serving_requests_total",
                            "Serving requests by outcome",
                            labels=("model", "outcome"))
    rps = _registry.gauge("mxtpu_serving_rps",
                          "Completion-window requests/s", labels=("model",))
    lat = _registry.gauge("mxtpu_serving_latency_ms",
                          "Recent-window latency percentiles",
                          labels=("model", "quantile"))
    depth = _registry.gauge("mxtpu_serving_queue_depth",
                            "Rows waiting for a batch", labels=("model",))
    fill = _registry.gauge("mxtpu_serving_batch_fill_ratio",
                           "Real rows / padded rows", labels=("model",))
    batches = _registry.counter("mxtpu_serving_batches_total",
                                "Compiled batches executed",
                                labels=("model",))
    stalls = _registry.counter("mxtpu_serving_stalled_batches_total",
                               "Batches killed by a watchdog stall",
                               labels=("model",))
    dl_drop = _registry.counter(
        "mxtpu_serving_deadline_dropped_total",
        "Requests dropped before a batch slot: provably unable to meet "
        "their deadline (where: submit|queue)", labels=("model", "where"))
    dl_out = _registry.counter(
        "mxtpu_serving_deadline_outcomes_total",
        "Deadline-carrying requests answered, by outcome",
        labels=("model", "outcome"))
    cache_req = _registry.counter(
        "mxtpu_serving_cache_requests_total",
        "Prediction-cache lookups by outcome",
        labels=("model", "outcome"))
    cache_ratio = _registry.gauge(
        "mxtpu_serving_cache_hit_ratio",
        "Prediction-cache hits / lookups (lifetime)", labels=("model",))
    coalesced = _registry.counter(
        "mxtpu_serving_coalesced_total",
        "Content-identical requests folded onto an in-flight leader",
        labels=("model",))
    class_lat = _registry.gauge(
        "mxtpu_serving_class_latency_ms",
        "Recent-window latency percentiles by QoS class",
        labels=("model", "class", "quantile"))
    for srv in mod.live_stats():
        for model, m in srv.get("models", {}).items():
            for outcome in ("submitted", "completed", "rejected",
                            "failed"):
                req.set_total(m.get(outcome, 0), model, outcome)
            if m.get("rps") is not None:
                rps.set(m["rps"], model)
            for q in ("p50", "p95", "p99"):
                v = m.get(f"{q}_ms")
                if v is not None:
                    lat.set(v, model, q)
            depth.set(m.get("queue_depth", 0), model)
            if m.get("batch_fill_ratio") is not None:
                fill.set(m["batch_fill_ratio"], model)
            batches.set_total(m.get("batches", 0), model)
            stalls.set_total(m.get("stalled_batches", 0), model)
            for where, n in (m.get("deadline_dropped") or {}).items():
                dl_drop.set_total(n, model, where)
            dl_out.set_total(m.get("deadline_met", 0), model, "met")
            dl_out.set_total(m.get("deadline_missed", 0), model,
                             "missed")
            cache_req.set_total(m.get("cache_hits", 0), model, "hit")
            cache_req.set_total(m.get("cache_misses", 0), model, "miss")
            if m.get("cache_hit_ratio") is not None:
                cache_ratio.set(m["cache_hit_ratio"], model)
            coalesced.set_total(m.get("coalesced", 0), model)
            for klass, cm in (m.get("by_class") or {}).items():
                for q in ("p50", "p99"):
                    v = cm.get(f"{q}_ms")
                    if v is not None:
                        class_lat.set(v, model, klass, q)


def _collect_watchdog():
    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is not None:
        _registry.counter(
            "mxtpu_watchdog_stalls_total",
            "Deadline-blown sync points (crash bundles written)"
        ).set_total(prof._stall_count)
    wd = sys.modules.get("mxnet_tpu.watchdog")
    if wd is not None:
        _registry.gauge("mxtpu_watchdog_enabled",
                        "1 when a watchdog deadline config is installed"
                        ).set(1.0 if wd.enabled() else 0.0)


def _collect_kvstore():
    mod = sys.modules.get("mxnet_tpu.kvstore.kvstore")
    if mod is None or not hasattr(mod, "OP_COUNTS"):
        return
    ops = _registry.counter("mxtpu_kvstore_ops_total",
                            "KVStore operations", labels=("op",))
    for op, n in mod.OP_COUNTS.items():
        ops.set_total(n, op)
    bmod = sys.modules.get("mxnet_tpu.kvstore.buckets")
    if bmod is None:
        return
    cs = bmod.comm_stats()
    if not cs["pipelines"]:
        return
    _registry.counter("mxtpu_kvstore_fused_collectives_total",
                      "Fused bucket collectives dispatched").set_total(
                          cs["fused"])
    _registry.counter("mxtpu_kvstore_bucketed_keys_total",
                      "Key payloads that rode a fused bucket").set_total(
                          cs["keys"])
    _registry.counter("mxtpu_kvstore_bucket_bytes_total",
                      "Bytes moved through fused bucket collectives"
                      ).set_total(cs["bytes"])
    _registry.gauge("mxtpu_kvstore_pending_buckets",
                    "Bucket reductions currently in flight "
                    "(dispatched, unresolved)").set(cs["pending"])
    if cs["overlap_ratio"] is not None:
        _registry.gauge(
            "mxtpu_kvstore_overlap_ratio",
            "1 - blocked/in-flight over fused reductions (1.0 = "
            "cross-host gradient sync fully hidden behind compute)"
        ).set(cs["overlap_ratio"])


def _collect_memory():
    _memory.sample(reason="scrape")
    tracked = _registry.gauge("mxtpu_executables_tracked",
                              "Distinct executables with captured "
                              "XLA analyses", labels=("site",))
    temp = _registry.gauge("mxtpu_executable_temp_bytes",
                           "Sum of XLA temp bytes over tracked "
                           "executables", labels=("site",))
    for site, agg in _costs.aggregate().items():
        tracked.set(agg["executables"], site)
        temp.set(agg["temp_bytes"], site)


def _collect_flight():
    ev = _registry.counter("mxtpu_flight_events_total",
                           "Flight-recorder events", labels=("kind",))
    for kind, n in _flight.counts().items():
        ev.set_total(n, kind)
    _registry.gauge("mxtpu_flight_ring_size",
                    "Flight-recorder capacity (0 = disabled)").set(
                        _flight.size())


def _collect_trace():
    spans = _registry.counter("mxtpu_trace_spans_total",
                              "Committed trace spans", labels=("kind",))
    for kind, n in _trace.counts().items():
        spans.set_total(n, kind)
    _registry.gauge("mxtpu_trace_ring_size",
                    "Span-ring capacity (0 = tracing disabled)").set(
                        _trace.size())


def _collect_modelbus():
    mod = sys.modules.get("mxnet_tpu.modelbus")
    if mod is None:
        return
    st = mod.stats()
    for key, help_ in (
            ("published", "Bus update records published"),
            ("applied", "Bus versions applied to live served models"),
            ("rejected", "Bus versions rejected + quarantined by a "
                         "subscriber (CRC / census / finiteness)"),
            ("rollbacks", "Rollback re-publications of a good version "
                          "after a quarantined head"),
            ("torn_skips", "Torn/partial bus records skipped "
                           "(warn-once latched)"),
            ("publish_skipped_nonfinite", "Updates the publisher's "
                                          "finite gate refused")):
        _registry.counter(f"mxtpu_modelbus_{key}_total",
                          help_).set_total(st.get(key, 0))
    ver = _registry.gauge("mxtpu_serving_model_version",
                          "Model-bus version pinned by each served "
                          "model (0 = load-time weights)",
                          labels=("model",))
    srv = sys.modules.get("mxnet_tpu.serving.server")
    if srv is not None:
        for s in srv.live_servers():
            for m in s.container:
                ver.set(m.version, m.name)
    age = _registry.gauge("mxtpu_serving_model_age_steps",
                          "Bounded staleness: newest published trainer "
                          "step minus the applied one, per watcher",
                          labels=("worker",))
    for w in mod.live_watchers():
        age.set(w.age_steps(), w.worker)


def _collect_preempt():
    mod = sys.modules.get("mxnet_tpu.preempt")
    if mod is None:
        return
    _registry.gauge("mxtpu_preempt_drain_requested",
                    "1 once a preemption drain has been requested").set(
                        1.0 if mod.requested() else 0.0)


def _collect_gang():
    mod = sys.modules.get("mxnet_tpu.elastic")
    if mod is None:
        return
    st = mod.GANG_STATS
    if st.get("state") == "idle":
        return  # neither supervising nor supervised in this process
    _registry.gauge("mxtpu_gang_generation",
                    "Current gang incarnation (bumps on every "
                    "coordinated restart)").set(st.get("generation", 0))
    _registry.gauge("mxtpu_gang_state_code",
                    "Gang state machine position "
                    "(mxnet_tpu.elastic.STATE_CODES)").set(
                        mod.STATE_CODES.get(st.get("state"), -1))
    _registry.gauge("mxtpu_gang_workers_alive",
                    "Worker processes currently alive under the "
                    "supervisor").set(st.get("workers_alive", 0))
    restarts = _registry.counter("mxtpu_gang_restarts_total",
                                 "Gang coordinated restarts by trigger",
                                 labels=("reason",))
    for reason, n in st.get("restarts", {}).items():
        restarts.set_total(n, reason)
    _registry.counter("mxtpu_gang_degraded_seconds_total",
                      "Wall-clock spent DEGRADED (a rank lost, gang "
                      "draining/restarting)").set_total(
                          st.get("degraded_s", 0.0))
    _registry.counter("mxtpu_gang_postmortems_total",
                      "Structured give-up bundles written").set_total(
                          st.get("postmortems", 0))


def _ensure_defaults():
    global _defaults_installed
    if _defaults_installed:
        return
    _defaults_installed = True
    register_collector("compile", _collect_compile)
    register_collector("serving", _collect_serving)
    register_collector("watchdog", _collect_watchdog)
    register_collector("kvstore", _collect_kvstore)
    register_collector("memory", _collect_memory)
    register_collector("flight", _collect_flight)
    register_collector("trace", _collect_trace)
    register_collector("modelbus", _collect_modelbus)
    register_collector("preempt", _collect_preempt)
    register_collector("gang", _collect_gang)


# ------------------------------------------------------ standalone server ---

class MetricsServer:
    """A loopback HTTP endpoint exposing ``/metrics`` (Prometheus text),
    ``/metrics.json`` and ``/healthz`` for processes that do not run the
    serving front end (trainers, the gang supervisor). ``port=0`` picks
    a free one."""

    def __init__(self, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "mxtpu-metrics/0.1"

            def log_message(self, *args):
                pass

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path in ("/metrics", "/"):
                    self._send(200, render_prometheus(),
                               PROMETHEUS_CONTENT_TYPE)
                elif self.path == "/metrics.json":
                    self._send(200, render_json(), "application/json")
                elif self.path == "/healthz":
                    self._send(200, '{"status": "ok"}',
                               "application/json")
                else:
                    self._send(404, f'{{"error": "no route '
                                    f'{self.path}"}}', "application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1}, daemon=True,
                name="mxtpu-metrics-http")
            self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
