"""Fleet metric aggregation + straggler detection over rank shards.

PR 10's gang gave every worker a heartbeat file; this module gives it a
**telemetry shard** next to it: every heartbeat, each rank atomically
rewrites ``telemetry-rank-<r>.json`` in the shared run dir with its
post-collection metrics snapshot (the SAME values its own ``/metrics``
scrape would serve), its recent step records, its span-ring tail and its
flight tail — plus the (t_wall, t_mono) clock pair the multi-rank trace
merge aligns on.

The supervisor side consumes them two ways:

* **Fleet scrape** — :func:`install` registers a scrape-time collector
  that folds every (non-torn) shard into ``mxtpu_fleet_*`` series on
  ONE endpoint (``tools/launch.py --supervise --metrics-port``):
  counters are summed across ranks (``mxtpu_fleet_<name>`` — the sums
  agree with the per-rank scrapes, test-asserted), a curated set of
  gauges is re-exported per rank (``rank`` label), and
  ``mxtpu_fleet_ranks`` / ``mxtpu_fleet_shard_age_seconds{rank}``
  report shard liveness.

* **Straggler verdict** — :class:`StragglerDetector` compares the ranks'
  recent *common* steps: per-step skew (max−min duration), per-rank
  sync-wait share, and a slowest-rank score (mean step time ÷ the other
  ranks' median). A rank scoring ≥ ``MXNET_TPU_STRAGGLER_FACTOR``
  (default 1.5) across ``MXNET_TPU_STRAGGLER_PERSIST`` (default 3)
  consecutive *new* common steps is flagged **persistent**: the
  ``mxtpu_gang_straggler_*`` gauges name it and a ``gang.straggler``
  flight event is recorded once per episode. The GangSupervisor runs
  the same detector from its monitor loop, so the verdict exists even
  when nobody scrapes.

Torn or partial shards (a rank mid-replace, a truncated file) are
skipped at read time — merging must never trust a half-written rank.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import _state, flight as _flight, registry as _registry
from . import steps as _steps, trace as _trace

__all__ = ["SHARD_PREFIX", "shard_path", "set_shard_info", "write_shard",
           "read_shards", "StragglerDetector", "detector", "install",
           "uninstall", "installed_dir", "verdict", "shard_ages",
           "describe"]

SHARD_PREFIX = "telemetry-rank-"

#: gauges re-exported per rank on the fleet endpoint (full generality
#: would explode label cardinality; counters are summed generically)
PER_RANK_GAUGES = ("mxtpu_step_time_ms", "mxtpu_step_mfu_xla",
                   "mxtpu_serving_queue_depth", "mxtpu_serving_rps")

_lock = threading.Lock()
_INFO: dict = {}         # extra shard fields (metrics_port, ...)
_seq = 0
_detector = None
_installed_dir = None


def shard_path(run_dir, rank):
    return os.path.join(os.fspath(run_dir), f"{SHARD_PREFIX}{rank}.json")


def set_shard_info(**fields):
    """Merge extra fields into every future shard this process writes
    (e.g. ``metrics_port`` so the fleet side can find the rank's own
    scrape endpoint)."""
    with _lock:
        _INFO.update(fields)


def _atomic_json(path, obj):
    # pid alone is not unique enough: the gang heartbeat thread and a
    # final main-thread write_shard can race on the same tmp name, and
    # the loser's os.replace dies with FileNotFoundError (worker exit 1)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, default=repr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def write_shard(run_dir, rank=None, generation=None):
    """Atomically (re)write this rank's telemetry shard. Runs the scrape
    collectors first so the snapshot equals what the rank's own
    ``/metrics`` endpoint would serve. No-op (returns None) when
    telemetry is disabled."""
    if not _state.enabled:
        return None
    if rank is None or generation is None:
        r, g = _trace.coords()
        rank = r if rank is None else rank
        generation = g if generation is None else generation
    from . import export as _export

    _export.collect()
    global _seq
    with _lock:
        _seq += 1
        seq = _seq
        info = dict(_INFO)
    shard = {"version": 1, "rank": int(rank),
             "generation": int(generation), "pid": os.getpid(),
             "seq": seq, "t_wall": time.time(),
             "t_mono": time.monotonic(),
             "metrics": _registry.snapshot(),
             "steps": _steps.history(32),
             "spans": _trace.tail(512),
             "flight": _flight.tail(64)}
    shard.update(info)
    os.makedirs(os.fspath(run_dir), exist_ok=True)
    return _atomic_json(shard_path(run_dir, int(rank)), shard)


def read_shards(run_dir, generation=None):
    """Parse every ``telemetry-rank-<r>.json`` under `run_dir` into
    ``{rank: shard}``. Torn, truncated or malformed shards are SKIPPED
    (the writer is mid-replace, or a rank died mid-write) — a merge must
    only ever see complete shards. ``generation`` filters to one gang
    incarnation.

    A multi-host serving fleet gives every host its own ``host-<name>/``
    subdirectory under the fleet run dir; those are scanned too (slot /
    rank ids are globally unique across hosts, so the merge is a plain
    union)."""
    out = {}
    run_dir = os.fspath(run_dir)
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    dirs = [run_dir] + sorted(
        os.path.join(run_dir, n) for n in names
        if n.startswith("host-")
        and os.path.isdir(os.path.join(run_dir, n)))
    for d in dirs:
        try:
            entries = names if d == run_dir else os.listdir(d)
        except OSError:
            continue
        for name in entries:
            if not (name.startswith(SHARD_PREFIX)
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    shard = json.load(f)
                rank = int(shard["rank"])
                float(shard["t_wall"]), float(shard["t_mono"])
            except (OSError, ValueError, TypeError, KeyError):
                continue
            if not isinstance(shard.get("metrics", {}), dict):
                continue
            if generation is not None \
                    and shard.get("generation") != generation:
                continue
            out[rank] = shard
    return out


def shard_ages(run_dir):
    """{rank: seconds since the shard was written} (diagnose)."""
    now = time.time()
    return {rank: round(now - float(sh["t_wall"]), 3)
            for rank, sh in read_shards(run_dir).items()}


# ------------------------------------------------------------- straggler ---

def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


class StragglerDetector:
    """Cross-rank per-step skew analysis over the shards' step records.

    A verdict only uses steps COMMON to every reporting rank (the gang
    trains one global step sequence, so common steps are the comparable
    unit); the persistence streak advances only when a NEW common step
    appears, so re-reading unchanged shards can never inflate it."""

    def __init__(self, factor=None, persist=None, window=8):
        self.factor = _env_float("MXNET_TPU_STRAGGLER_FACTOR", 1.5) \
            if factor is None else float(factor)
        self.persist = int(_env_float("MXNET_TPU_STRAGGLER_PERSIST", 3)) \
            if persist is None else int(persist)
        self.window = int(window)
        self.last = None
        self.events = 0
        self._streak_rank = None
        self._streak = 0
        self._last_step = -1
        self._episode_recorded = False

    def update(self, shards):
        """Recompute the verdict from ``{rank: shard}``; returns it."""
        hist = {}
        for rank, sh in shards.items():
            recs = {}
            for r in sh.get("steps") or []:
                if isinstance(r, dict) and "step" in r \
                        and "duration_ms" in r:
                    recs[int(r["step"])] = r
            if recs:
                hist[int(rank)] = recs
        if len(hist) < 2:
            self.last = {"status": "insufficient-ranks",
                         "ranks": sorted(hist)}
            return self.last
        common = set.intersection(*(set(h) for h in hist.values()))
        if not common:
            self.last = {"status": "no-common-steps",
                         "ranks": sorted(hist)}
            return self.last
        steps_common = sorted(common)[-self.window:]
        last_step = steps_common[-1]
        per_rank = {}
        for rank, recs in hist.items():
            durs = [float(recs[s]["duration_ms"]) for s in steps_common]
            syncs = [float((recs[s].get("phases") or {}).get("sync", 0.0))
                     for s in steps_common]
            per_rank[rank] = {
                "mean_step_ms": round(sum(durs) / len(durs), 3),
                "last_step_ms": round(float(
                    recs[last_step]["duration_ms"]), 3),
                "sync_share": round(sum(syncs) / max(1e-9, sum(durs)), 4)}
        means = {r: v["mean_step_ms"] for r, v in per_rank.items()}
        slowest = max(means, key=lambda r: means[r])
        others = sorted(v for r, v in means.items() if r != slowest)
        median_others = others[len(others) // 2]
        for r, v in per_rank.items():
            v["score"] = round(means[r] / max(1e-9, median_others), 3)
        score = per_rank[slowest]["score"]
        lasts = [v["last_step_ms"] for v in per_rank.values()]
        skew = max(lasts) - min(lasts)
        flagged = score >= self.factor
        if last_step > self._last_step:
            self._last_step = last_step
            if flagged and slowest == self._streak_rank:
                self._streak += 1
            elif flagged:
                self._streak_rank, self._streak = slowest, 1
            else:
                self._streak_rank, self._streak = None, 0
                self._episode_recorded = False
        persistent = (self._streak_rank is not None
                      and self._streak >= self.persist)
        if persistent and not self._episode_recorded:
            self._episode_recorded = True
            self.events += 1
            _flight.rec("gang.straggler", f"rank{self._streak_rank}",
                        f"score {score:.2f} skew {skew:.1f}ms at step "
                        f"{last_step}")
        self.last = {"status": "ok", "ranks": sorted(hist),
                     "last_common_step": last_step,
                     "steps_compared": len(steps_common),
                     "skew_ms": round(skew, 3),
                     "slowest_rank": slowest if flagged else None,
                     "score": score, "factor": self.factor,
                     "persistent": persistent, "streak": self._streak,
                     "per_rank": per_rank}
        return self.last


def detector():
    """The process-shared detector (created on first use) — the
    supervisor monitor loop and the fleet collector must feed the SAME
    streak, or persistence would double-count."""
    global _detector
    with _lock:
        if _detector is None:
            _detector = StragglerDetector()
        return _detector


def verdict():
    """The latest straggler verdict in this process, or None."""
    det = _detector
    return det.last if det is not None else None


# ------------------------------------------------------- fleet collector ---

def _fleet_name(name):
    return "mxtpu_fleet_" + (name[len("mxtpu_"):]
                             if name.startswith("mxtpu_") else name)


def _collect_fleet():
    run_dir = _installed_dir
    if run_dir is None:
        return
    shards = read_shards(run_dir)
    _registry.gauge("mxtpu_fleet_ranks",
                    "Rank telemetry shards readable at the last "
                    "scrape").set(len(shards))
    age = _registry.gauge("mxtpu_fleet_shard_age_seconds",
                          "Seconds since each rank's shard was written",
                          labels=("rank",))
    now = time.time()
    sums: dict = {}   # (name, labels tuple, label values) -> total
    for rank, sh in shards.items():
        age.set(max(0.0, now - float(sh["t_wall"])), rank)
        for name, metric in (sh.get("metrics") or {}).items():
            if not isinstance(metric, dict):
                continue
            kind = metric.get("kind")
            labels = tuple(metric.get("labels") or ())
            for series in metric.get("series") or ():
                try:
                    values = tuple(series["labels"].get(l, "")
                                   for l in labels)
                except (AttributeError, TypeError):
                    continue
                v = series.get("value")
                if kind == "counter" and isinstance(v, (int, float)):
                    key = (name, labels, values)
                    sums[key] = sums.get(key, 0.0) + float(v)
                elif kind == "gauge" and name in PER_RANK_GAUGES \
                        and isinstance(v, (int, float)):
                    _registry.gauge(
                        _fleet_name(name),
                        f"Per-rank re-export of {name}",
                        labels=labels + ("rank",)).set(v, *values, rank)
    for (name, labels, values), total in sums.items():
        _registry.counter(
            _fleet_name(name),
            f"Sum of {name} across rank shards",
            labels=labels).set_total(total, *values)
    # straggler verdict gauges ride on the same scrape
    det = detector()
    v = det.update(shards)
    _registry.gauge(
        "mxtpu_gang_straggler_rank",
        "Rank flagged slowest (score >= factor); -1 when none").set(
            v.get("slowest_rank") if v.get("slowest_rank") is not None
            else -1)
    _registry.gauge("mxtpu_gang_straggler_skew_ms",
                    "max-min duration of the last common step").set(
                        v.get("skew_ms", 0.0) or 0.0)
    _registry.gauge("mxtpu_gang_straggler_persistent",
                    "1 when the same rank stayed flagged across the "
                    "persistence window").set(
                        1.0 if v.get("persistent") else 0.0)
    _registry.counter("mxtpu_gang_straggler_events_total",
                      "Persistent-straggler flight events recorded"
                      ).set_total(det.events)
    per_rank = v.get("per_rank") or {}
    if per_rank:
        score = _registry.gauge("mxtpu_gang_straggler_score",
                                "Mean step time / other ranks' median",
                                labels=("rank",))
        share = _registry.gauge("mxtpu_gang_straggler_sync_share",
                                "Sync-wait share of recent step time",
                                labels=("rank",))
        stepms = _registry.gauge("mxtpu_gang_straggler_step_ms",
                                 "Mean step duration over the compared "
                                 "window", labels=("rank",))
        for rank, rec in per_rank.items():
            score.set(rec["score"], rank)
            share.set(rec["sync_share"], rank)
            stepms.set(rec["mean_step_ms"], rank)


def install(run_dir):
    """Point the fleet collector at `run_dir` and register it — every
    subsequent scrape in this process (supervisor MetricsServer, serving
    front end) folds the rank shards in. Returns the run dir."""
    global _installed_dir
    from . import export as _export

    _installed_dir = os.fspath(run_dir)
    _export.register_collector("fleet", _collect_fleet)
    return _installed_dir


def uninstall():
    """Deregister the fleet collector (tests)."""
    global _installed_dir
    from . import export as _export

    _installed_dir = None
    _export.unregister_collector("fleet")


def installed_dir():
    return _installed_dir


def describe():
    """Knobs + state (tools/diagnose.py "Tracing")."""
    det = _detector
    return {"installed_dir": _installed_dir,
            "shard_info": dict(_INFO),
            "factor": _env_float("MXNET_TPU_STRAGGLER_FACTOR", 1.5),
            "persist": int(_env_float("MXNET_TPU_STRAGGLER_PERSIST", 3)),
            "verdict": det.last if det is not None else None,
            "events": det.events if det is not None else 0}
