"""Flight recorder: an always-on, constant-memory ring of the last N
structured runtime events.

The profiler answers "what happened?" only when someone remembered to
turn it on *before* the incident. The flight recorder is the other half
of post-mortem observability: it is **always on** (like an aircraft
FDR), costs one module-global check plus a slot write per event, and its
tail ships inside every watchdog crash bundle (``flight.json``) and
every preemption drain event (``flight_tail``) — so a hang or a
preemption at 3am yields the last-N timeline of what the process was
doing with no profiling session required.

Recorded event kinds (the coarse seams, never the per-op hot path):

    ``step.begin`` / ``step.end``   trainer step boundaries (+ duration)
    ``sync``                        every watchdog-spanned blocking point
                                    (engine.flush, host.sync,
                                    trainer.step, io.fetch, kvstore.sync
                                    — collectives —, serving.batch)
    ``compile.miss``                compile-service miss (site + source)
    ``serving.reject``              admission fast-reject
    ``serving.batch`` / ``serving.stall``   served / wedged batch
    ``watchdog.warn`` / ``watchdog.stall``  escalation ladder steps
    ``preempt.request`` / ``preempt.drain`` preemption lifecycle
    ``io.error``                    prefetch worker failure
    ``oom``                         RESOURCE_EXHAUSTED surfaced
    ``modelbus.*``                  live-weight-bus lifecycle (publish,
                                    apply, reject, rollback, torn_skip,
                                    skip_nonfinite) — a crash bundle
                                    shows the last applied/rejected
                                    model version
    ``gang.*``                      elastic gang lifecycle (state, spawn,
                                    exit, restart, peer_lost, peer_kill,
                                    heartbeat_lost, postmortem)

Memory contract: the ring is a preallocated list of fixed slot lists
written **in place** — after the first lap no list/dict/tuple is
allocated per event (only the unavoidable float/str objects for the
fields themselves), so a multi-week serving process holds exactly
``MXNET_TPU_FLIGHT`` (default 1024; 0 disables) events forever.

Lock-light: writers claim slots via an atomic counter
(``itertools.count`` — C-implemented, GIL-atomic) and write their slot
without a lock. A reader racing a writer can observe one torn slot;
:func:`tail` drops slots whose sequence number is inconsistent, which is
the right trade for a recorder that must never stall the recorded.
"""
from __future__ import annotations

import itertools
import os
import time

from . import _state

__all__ = ["rec", "tail", "counts", "size", "clear"]

try:
    _N = int(os.environ.get("MXNET_TPU_FLIGHT", "1024"))
except ValueError:
    _N = 1024
_N = max(0, _N)

# slot layout: [seq, t_mono, t_wall, kind, point, label]
_ring = [[-1, 0.0, 0.0, "", "", None] for _ in range(_N)]
_seq = itertools.count()
_counts: dict = {}


def rec(kind, point="", label=None):
    """Record one event (no-op when telemetry is disabled or the ring
    size is 0). ``label`` may be any short printable value — it lands in
    crash bundles verbatim."""
    if not _state.enabled or _N == 0:
        return
    i = next(_seq)
    slot = _ring[i % _N]
    slot[0] = -1  # invalidate while torn
    slot[1] = time.monotonic()
    slot[2] = time.time()
    slot[3] = kind
    slot[4] = point
    slot[5] = label
    slot[0] = i   # publish
    # lossy-tolerable totals: a racing increment may drop one count, the
    # ring itself is exact (seq-claimed slots) — not worth a lock on the
    # every-event hot path
    _counts[kind] = _counts.get(kind, 0) + 1  # concur: atomic


def tail(n=None):
    """The last ``n`` (default: all retained) events as JSON-able dicts,
    oldest first. Torn or empty slots are skipped."""
    events = []
    for slot in _ring:
        seq, t_mono, t_wall, kind, point, label = slot
        if seq < 0:
            continue
        events.append({"seq": seq, "t_mono": round(t_mono, 6),
                       "t_wall": round(t_wall, 6), "kind": kind,
                       "point": point, "label": label})
    events.sort(key=lambda e: e["seq"])
    if n is not None:
        events = events[-int(n):]
    return events


def counts():
    """Process-lifetime event totals per kind (feeds the
    ``mxtpu_flight_events_total`` metric series)."""
    return dict(_counts)


def size():
    """Ring capacity (``MXNET_TPU_FLIGHT``; 0 = disabled)."""
    return _N


def clear():
    """Drop all retained events and counts (tests)."""
    for slot in _ring:
        slot[0] = -1
    _counts.clear()
