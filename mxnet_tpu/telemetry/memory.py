"""Device-memory telemetry: live/peak byte gauges + OOM forensics.

A framework whose whole point is TPU HBM had, before this module, zero
visibility into it. Two sources, used in this order:

* **allocator stats** — ``device.memory_stats()`` (TPU/GPU runtimes):
  ``bytes_in_use`` / ``peak_bytes_in_use`` per local device, the real
  HBM numbers;
* **host fallback** — CPU jaxlib returns no allocator stats, so the
  fallback sums ``jax.live_arrays()`` (the process's live framework
  buffers) under one ``host`` pseudo-device, with the peak tracked
  host-side. An estimate, but it moves with the working set and keeps
  the ``mxtpu_device_memory_*`` series populated on fallback hosts.

Samples are taken at trainer step boundaries (every
``MXNET_TPU_TELEMETRY_MEMSAMPLE``-th step, default 1; 0 disables) and at
every ``/metrics`` scrape, so a serving-only process reports memory too.

OOM forensics: :func:`oom_report` combines the live sample with the
top-K resident executables by XLA ``memory_analysis()`` (captured at
compile time by :mod:`mxnet_tpu.compile` into
:mod:`mxnet_tpu.telemetry.costs`) — the first thing to read when a pod
dies RESOURCE_EXHAUSTED. The watchdog embeds it in every crash bundle.
"""
from __future__ import annotations

import os
import threading

from . import _state, costs as _costs, registry as _registry

__all__ = ["sample", "device_memory", "top_executables", "oom_report",
           "maybe_sample_step", "sample_every"]

_lock = threading.Lock()
_host_peak = 0
_last_sample = None


def sample_every() -> int:
    """Step-boundary sampling period (0 disables step sampling)."""
    try:
        return max(0, int(os.environ.get("MXNET_TPU_TELEMETRY_MEMSAMPLE",
                                         "1")))
    except ValueError:
        return 1


def device_memory():
    """One record per local device: ``{device, platform, live_bytes,
    peak_bytes, source}``. Never raises — an unreachable backend yields
    an empty list."""
    global _host_peak
    out = []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    fallback = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out.append({
                "device": f"{d.platform}:{d.id}",
                "platform": d.platform,
                "live_bytes": int(stats.get("bytes_in_use", 0)),
                "peak_bytes": int(stats.get("peak_bytes_in_use",
                                            stats.get("bytes_in_use", 0))),
                "source": "memory_stats",
            })
        else:
            fallback.append(d)
    if fallback and not out:
        try:
            import jax

            live = sum(a.nbytes for a in jax.live_arrays())
        except Exception:
            return out
        with _lock:
            _host_peak = max(_host_peak, live)
            peak = _host_peak
        out.append({"device": "host", "platform": fallback[0].platform,
                    "live_bytes": int(live), "peak_bytes": int(peak),
                    "source": "live_arrays"})
    return out


def sample(reason="scrape"):
    """Take one sample and publish the live/peak gauges. Returns the
    per-device records (None when telemetry is disabled)."""
    global _last_sample
    if not _state.enabled:
        return None
    recs = device_memory()
    if recs:
        live = _registry.gauge(
            "mxtpu_device_memory_live_bytes",
            "Live device (or host-fallback) bytes at the last sample",
            labels=("device",))
        peak = _registry.gauge(
            "mxtpu_device_memory_peak_bytes",
            "Peak device (or host-fallback) bytes observed",
            labels=("device",))
        for r in recs:
            live.set(r["live_bytes"], r["device"])
            peak.set(r["peak_bytes"], r["device"])
    _last_sample = {"reason": reason, "devices": recs}
    return recs


def last_sample():
    """The most recent sample (diagnose), or None."""
    return _last_sample


_step_counter = 0


def maybe_sample_step():
    """Step-boundary sampling hook (called by the trainer step timeline);
    honours the ``MXNET_TPU_TELEMETRY_MEMSAMPLE`` period."""
    global _step_counter
    n = sample_every()
    if n == 0:
        return None
    _step_counter += 1
    if _step_counter % n:
        return None
    return sample(reason="step")


def top_executables(k=10):
    """The K most memory-resident executables the compile service has
    built, by XLA-analyzed ``temp + output + generated-code`` bytes —
    what is plausibly *still resident* and worth evicting/resharding
    when HBM runs out."""
    recs = _costs.records()

    def resident(r):
        return (r.get("temp_bytes", 0) or 0) \
            + (r.get("output_bytes", 0) or 0) \
            + (r.get("generated_code_bytes", 0) or 0)

    recs = [r for r in recs if resident(r) > 0]
    recs.sort(key=resident, reverse=True)
    out = []
    for r in recs[:k]:
        out.append({"site": r["site"], "token": r["token"],
                    "resident_bytes": resident(r),
                    "temp_bytes": r.get("temp_bytes", 0),
                    "output_bytes": r.get("output_bytes", 0),
                    "argument_bytes": r.get("argument_bytes", 0),
                    "generated_code_bytes":
                        r.get("generated_code_bytes", 0)})
    return out


def oom_report(k=10):
    """The OOM post-mortem: live per-device sample + top-K resident
    executables + per-site aggregates. Embedded in watchdog crash
    bundles and printed by ``tools/diagnose.py``."""
    return {"devices": device_memory(),
            "top_executables": top_executables(k),
            "aggregate": _costs.aggregate()}
