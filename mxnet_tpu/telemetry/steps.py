"""Per-step phase timeline: data-wait / h2d / compute / optimizer / sync.

One record per ``ShardedTrainer.step``, assembled from the existing
instrumentation seams rather than new ones:

    ``data_wait``  time the consumer blocked on the input pipeline
                   (PrefetchingIter's staged-batch join — reported by
                   ``io/io.py`` into the *next* step's record);
    ``h2d``        host-to-device placement of the batch
                   (``_put_batch``; ~0 when the prefetcher device-staged);
    ``compute``    the compiled step call — dispatch plus, when the
                   nan-guard's flag read synchronizes, device execution.
                   The fused step runs fwd+bwd+optimizer as ONE
                   executable, so the optimizer phase is folded in here;
    ``optimizer``  a separate optimizer executable's time (0 for the
                   fused ShardedTrainer step — present so the grammar is
                   stable across trainer styles);
    ``sync``       explicit post-step host reads (the nan-guard skip-flag
                   read). With ``nan_guard=False`` dispatch is async and
                   both compute and sync shrink toward dispatch cost —
                   wall-clock then shows up in the NEXT step's phases.

Each finished step publishes gauges (``mxtpu_step_time_ms``,
``mxtpu_step_phase_ms{phase}``), a duration histogram, a running step
counter, and — when the compile service captured ``cost_analysis()``
flops for the step executable — ``mxtpu_step_mfu_xla`` (measured flops ÷
the per-device-kind peak table), plus ``step.begin``/``step.end`` flight
events. ``bench.py`` and ``ShardedTrainer.step_report()`` read the same
records.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from . import _state, costs as _costs, flight as _flight
from . import registry as _registry

__all__ = ["PHASES", "begin_step", "phase", "end_step", "abort", "last",
           "history", "reset"]

PHASES = ("data_wait", "h2d", "compute", "optimizer", "sync")

_lock = threading.Lock()
_HIST = deque(maxlen=256)
_cur = None
_pending: dict = {}   # phases measured before the step opened (data_wait)


def begin_step(step):
    """Open the record for `step` (folds in pending pre-step phases)."""
    global _cur
    if not _state.enabled:
        return
    phases = dict.fromkeys(PHASES, 0.0)
    with _lock:
        phases.update(_pending)
        _pending.clear()
    _cur = {"step": int(step), "t0": time.monotonic(), "phases": phases}
    _flight.rec("step.begin", "trainer.step", int(step))


def phase(name, ms):
    """Accrue `ms` into phase `name` of the open step — or, with no step
    open (the prefetcher measuring data-wait between steps), into the
    next one."""
    if not _state.enabled:
        return
    cur = _cur
    if cur is not None:
        cur["phases"][name] = cur["phases"].get(name, 0.0) + ms
    else:
        with _lock:
            _pending[name] = _pending.get(name, 0.0) + ms


def abort():
    """Discard the open record (the step raised — an injected fault, a
    drain request, a stall); its partial phases must not skew the
    timeline."""
    global _cur
    _cur = None


def end_step(flops=None, devices=1, device_kind=None):
    """Close the open record: total duration, phase splits, measured-MFU
    when `flops` (per-invocation, from ``cost_analysis``) is known.
    Publishes the step gauges and returns the record (None when no step
    is open)."""
    global _cur
    cur = _cur
    if cur is None:
        return None
    _cur = None
    dur_ms = (time.monotonic() - cur["t0"]) * 1e3
    rec = {"step": cur["step"], "duration_ms": round(dur_ms, 3),
           "phases": {k: round(v, 3) for k, v in cur["phases"].items()},
           "t_wall": time.time()}
    accounted = sum(cur["phases"].values())
    rec["phases"]["other"] = round(max(0.0, dur_ms - accounted), 3)
    if flops:
        rec["flops"] = flops
        mfu = _costs.mfu_xla(flops, 1e3 / dur_ms if dur_ms > 0 else 0.0,
                             devices=devices, device_kind=device_kind)
        if mfu is not None:
            rec["mfu_xla"] = round(mfu, 5)
    _HIST.append(rec)
    _registry.counter("mxtpu_train_steps_total",
                      "Trainer steps completed").inc()
    _registry.gauge("mxtpu_step_time_ms",
                    "Duration of the last trainer step").set(dur_ms)
    ph = _registry.gauge("mxtpu_step_phase_ms",
                         "Phase split of the last trainer step",
                         labels=("phase",))
    for k, v in rec["phases"].items():
        ph.set(v, k)
    _registry.histogram("mxtpu_step_time_ms_hist",
                        "Trainer step duration distribution").observe(
                            dur_ms)
    if rec.get("mfu_xla") is not None:
        _registry.gauge(
            "mxtpu_step_mfu_xla",
            "Measured-flops MFU of the last step (cost_analysis ÷ "
            "per-device-kind peak)").set(rec["mfu_xla"])
        _registry.gauge("mxtpu_step_flops",
                        "XLA-analyzed flops per step").set(flops)
    # the step's span twin, keyed (generation, rank, step) — the raw
    # material of the fleet straggler verdict and the merged gang trace
    from . import trace as _trace

    _trace.step_span(rec, cur["t0"])
    _flight.rec("step.end", "trainer.step",
                f"step {rec['step']} {rec['duration_ms']}ms")
    from . import memory as _memory

    _memory.maybe_sample_step()
    return rec


def last():
    """The most recent finished step record, or None."""
    return dict(_HIST[-1]) if _HIST else None


def history(n=None):
    """The last `n` (default all retained) step records, oldest first."""
    items = list(_HIST)
    if n is not None:
        items = items[-int(n):]
    return [dict(r) for r in items]


def reset():
    """Drop records and pending phases (tests)."""
    global _cur
    with _lock:
        _pending.clear()
    _cur = None
    _HIST.clear()
