"""Shared telemetry switch — one module-global every submodule reads.

Kept in its own tiny dependency-free module so the hot-path check in
:func:`mxnet_tpu.telemetry.flight.rec` (and the step/memory
instrumentation) is a single attribute load, and so no submodule has to
import the package ``__init__`` (which imports all of them).

``MXNET_TPU_TELEMETRY=0`` disables every *push* instrumentation point
(flight recorder, step breakdown, memory sampling, executable
cost/memory capture) at process start; :func:`set_enabled` flips it at
runtime (the A/B perf-gate seam). Pull-based exports (the metrics
registry collectors) always answer a scrape — they only read counters
other subsystems already keep.
"""
from __future__ import annotations

import os

enabled = os.environ.get("MXNET_TPU_TELEMETRY", "1").lower() \
    not in ("0", "false", "off")


def set_enabled(on) -> bool:
    """Toggle push instrumentation; returns the previous state."""
    global enabled
    prev = enabled
    enabled = bool(on)
    return prev
