"""Span tracer with propagated context: per-request and per-step
timelines that survive across threads and — through the fleet shard
channel — across ranks.

The metrics registry answers "how much / how fast on average"; the
flight recorder answers "what was the process doing just before it
died". Neither answers "where did THIS request spend its 5ms?" or
"which rank is slow, and in which phase?". Spans do:

* **Serving requests** carry a request id from the HTTP front end (or a
  fresh one minted at ``submit``) through the batcher queue, the
  :class:`~mxnet_tpu.io.io.DeviceStager` h2d put, the compiled call and
  the response — producing a five-phase breakdown per request::

      queue_wait     submit -> popped by the batch collector
      batch_collect  coalescing + zero-padding into the bucket
      h2d            device staging of the padded batch
      compute        the compiled bucket execution (watchdog-spanned)
      respond        output slicing + future fulfilment

  The phases are exposed on the client handle
  (``ServingFuture.breakdown()``), in the HTTP response (``phases`` +
  ``request_id`` fields, ``X-Request-Id`` header echoed), and in
  ``tools/loadgen.py``'s per-phase percentile report.

* **Trainer steps** reuse the :mod:`~mxnet_tpu.telemetry.steps` phase
  timeline: every finished step commits one span keyed by
  ``(generation, rank, step)`` with its phase children — the raw
  material of the fleet-level straggler verdict
  (:mod:`~mxnet_tpu.telemetry.fleet`).

* **Ad-hoc spans** (:func:`span`) nest through a per-thread stack and
  inherit the thread's propagated trace context (:func:`context`).

Committed spans live in a bounded ring (``MXNET_TPU_TRACE``, default
2048 spans; 0 disables tracing entirely). Overhead contract: tracing
off = one module-global check per hook (:func:`enabled`); on, the cost
is per *request/step/batch*, never per op.

:func:`dump` folds spans, flight-recorder tails and (locally) the
profiler's chrome events into a ``trace.json`` that loads directly in
Perfetto / ``chrome://tracing`` — one lane (pid) per rank, clocks
aligned via the monotonic->wall offsets the telemetry shards carry.
``tools/traceview.py`` is the CLI over the multi-rank merge.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from . import _state

__all__ = ["enabled", "configure", "size", "new_request_id", "coords",
           "context", "set_context", "get_context", "span", "commit",
           "request_begin", "RequestTrace", "REQUEST_PHASES",
           "step_span", "tail", "counts", "clear", "dump", "last_dump",
           "merged_events", "describe"]

#: the serving request phase vocabulary, in pipeline order
REQUEST_PHASES = ("queue_wait", "batch_collect", "h2d", "compute",
                  "respond")

try:
    _N = int(os.environ.get("MXNET_TPU_TRACE", "2048"))
except ValueError:
    _N = 2048
_N = max(0, _N)

_ring = deque(maxlen=(_N or 1))
_seq = itertools.count()
_ids = itertools.count(1)
_counts: dict = {}
_counts_lock = threading.Lock()
_tls = threading.local()
_last_dump = None


def enabled() -> bool:
    """True when spans are being recorded (telemetry on AND ring > 0).
    The one check every tracing hook performs before doing any work."""
    return _state.enabled and _N > 0


def configure(size):
    """Resize the span ring at runtime (0 disables tracing; the A/B
    perf-gate seam). Returns the previous size."""
    global _N, _ring
    prev = _N
    _N = max(0, int(size))
    _ring = deque(maxlen=(_N or 1))
    with _counts_lock:
        _counts.clear()
    return prev


def size():
    """Ring capacity (``MXNET_TPU_TRACE``; 0 = tracing disabled)."""
    return _N


def coords():
    """(rank, generation) gang coordinates of this process — 0/0 outside
    a supervised gang (``MXTPU_WORKER_ID`` / ``MXTPU_GANG_GENERATION``
    are exported by the supervisor / launcher)."""
    try:
        rank = int(os.environ.get("MXTPU_WORKER_ID", "0") or 0)
    except ValueError:
        rank = 0
    try:
        gen = int(os.environ.get("MXTPU_GANG_GENERATION", "0") or 0)
    except ValueError:
        gen = 0
    return rank, gen


def new_request_id():
    """A process-unique request id (pid-prefixed atomic counter —
    ``itertools.count`` is C-implemented and GIL-atomic, so concurrent
    submits can never collide)."""
    return f"{os.getpid():x}-{next(_ids):x}"


# ------------------------------------------------------- context plumbing --

def set_context(trace_id):
    """Bind `trace_id` as this thread's propagated trace context (spans
    and requests created on this thread inherit it). Returns the
    previous binding."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace_id
    return prev


def get_context():
    """This thread's propagated trace id, or None."""
    return getattr(_tls, "trace", None)


class context:
    """``with trace.context(request_id): ...`` — scoped propagation (the
    HTTP front end wraps each handled request in one)."""

    def __init__(self, trace_id):
        self.trace_id = trace_id
        self._prev = None

    def __enter__(self):
        self._prev = set_context(self.trace_id)
        return self.trace_id

    def __exit__(self, *exc):
        _tls.trace = self._prev


# ------------------------------------------------------------- committing --

def commit(name, t0_mono, dur_ms, *, kind="span", trace_id=None,
           parent=None, lane=None, attrs=None):
    """Append one finished span to the ring (no-op when tracing is off).
    Returns the span id (None when off)."""
    if not enabled():
        return None
    sid = next(_seq)
    rec = {"seq": sid, "name": name, "kind": kind,
           "trace": trace_id if trace_id is not None else get_context(),
           "parent": parent,
           "t0": round(float(t0_mono), 6),
           "dur_ms": round(float(dur_ms), 4),
           "lane": int(lane) if lane is not None
           else (threading.get_ident() % 100000),
           "attrs": attrs or None}
    _ring.append(rec)
    with _counts_lock:
        _counts[kind] = _counts.get(kind, 0) + 1
    return sid


class span:
    """Measure a nested span: ``with trace.span("io.h2d"): ...``.
    Nesting is tracked per thread — an inner span's ``parent`` is the
    enclosing span's id, and both inherit the thread's trace context."""

    def __init__(self, name, kind="span", **attrs):
        self.name = name
        self.kind = kind
        self.attrs = attrs
        self.span_id = None
        self._t0 = None

    def __enter__(self):
        if enabled():
            self._t0 = time.monotonic()
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            # claim the id up front so children can reference it
            self.span_id = next(_seq)
            stack.append(self.span_id)
        return self

    def __exit__(self, *exc):
        if self._t0 is None:
            return
        stack = getattr(_tls, "stack", ())
        if stack and stack[-1] == self.span_id:
            stack.pop()
        parent = stack[-1] if stack else None
        if not enabled():
            return
        rec = {"seq": self.span_id, "name": self.name, "kind": self.kind,
               "trace": get_context(), "parent": parent,
               "t0": round(self._t0, 6),
               "dur_ms": round((time.monotonic() - self._t0) * 1e3, 4),
               "lane": threading.get_ident() % 100000,
               "attrs": self.attrs or None}
        _ring.append(rec)
        with _counts_lock:
            _counts[self.kind] = _counts.get(self.kind, 0) + 1


# -------------------------------------------------------- serving requests --

_lane = itertools.count()


class RequestTrace:
    """One serving request's propagated context: the batcher stamps
    monotonic marks as the request moves through the pipeline and
    :meth:`finish` turns them into the five-phase breakdown + committed
    spans. Marks are written by one thread at a time (submit thread ->
    collector -> runner), so no lock is needed."""

    __slots__ = ("request_id", "model", "rows", "marks", "breakdown",
                 "_lane")

    def __init__(self, request_id, model, rows=1):
        self.request_id = request_id
        self.model = model
        self.rows = rows
        self.marks = {"submit": time.monotonic()}
        self.breakdown = None
        self._lane = 1000 + next(_lane) % 256

    def mark(self, name, t=None):
        """Stamp pipeline mark `name` (submit / collected / assembled /
        staged / run_begin / run_end)."""
        self.marks[name] = time.monotonic() if t is None else t

    def _phase_bounds(self):
        m = self.marks
        return (("queue_wait", m.get("submit"), m.get("collected")),
                ("batch_collect", m.get("collected"), m.get("assembled")),
                ("h2d", m.get("assembled"), m.get("staged")),
                ("compute", m.get("run_begin"), m.get("run_end")),
                ("respond", m.get("run_end"), m.get("done")))

    def finish(self, error=None, bucket=None):
        """Close the request: compute the phase breakdown, commit the
        parent ``request`` span + one child span per measured phase."""
        self.mark("done")
        bd = {"request_id": self.request_id, "model": self.model,
              "rows": self.rows,
              "total_ms": round((self.marks["done"]
                                 - self.marks["submit"]) * 1e3, 4)}
        if error is not None:
            bd["error"] = str(error)
        if bucket is not None:
            bd["bucket"] = bucket
        for name, a, b in self._phase_bounds():
            bd[f"{name}_ms"] = round(max(0.0, (b - a) * 1e3), 4) \
                if (a is not None and b is not None) else None
        self.breakdown = bd
        if not enabled():
            return bd
        parent = commit(f"request[{self.model}]", self.marks["submit"],
                        bd["total_ms"], kind="request",
                        trace_id=self.request_id, lane=self._lane,
                        attrs={k: v for k, v in bd.items()
                               if k not in ("request_id", "model")})
        for name, a, b in self._phase_bounds():
            if a is None or b is None:
                continue
            commit(name, a, max(0.0, (b - a) * 1e3), kind="phase",
                   trace_id=self.request_id, parent=parent,
                   lane=self._lane)
        return bd


def request_begin(model, rows=1, request_id=None):
    """Open a :class:`RequestTrace` for one serving submit (None when
    tracing is off). The id is the thread's propagated context (the
    HTTP front end's ``X-Request-Id``) when bound, else freshly
    minted."""
    if not enabled():
        return None
    rid = request_id or get_context() or new_request_id()
    return RequestTrace(rid, model, rows=rows)


# ----------------------------------------------------------- trainer steps --

def step_span(rec, t0_mono):
    """Commit one trainer step as a span keyed ``(generation, rank,
    step)`` with its phase children laid out in pipeline order (called
    by :func:`mxnet_tpu.telemetry.steps.end_step`)."""
    if not enabled():
        return
    rank, gen = coords()
    trace_id = f"step-g{gen}-r{rank}-{rec['step']}"
    lane = 500 + (rank % 100)
    parent = commit("trainer.step", t0_mono, rec["duration_ms"],
                    kind="step", trace_id=trace_id, lane=lane,
                    attrs={"step": rec["step"], "rank": rank,
                           "generation": gen,
                           "phases": dict(rec["phases"])})
    # the phase split is accrued (durations, not timestamps); lay the
    # children out sequentially in the order they actually execute
    t = t0_mono
    for name in ("data_wait", "h2d", "compute", "optimizer", "sync",
                 "other"):
        ms = rec["phases"].get(name, 0.0)
        if ms <= 0.0:
            continue
        commit(name, t, ms, kind="phase", trace_id=trace_id,
               parent=parent, lane=lane)
        t += ms / 1e3


# ------------------------------------------------------------- inspection --

def tail(n=None):
    """The last `n` (default all retained) committed spans, oldest
    first, as JSON-able dicts."""
    items = list(_ring)
    if n is not None:
        items = items[-int(n):]
    return [dict(r) for r in items]


def counts():
    """Process-lifetime committed-span totals per kind."""
    with _counts_lock:
        return dict(_counts)


def clear():
    """Drop retained spans and counts (tests)."""
    _ring.clear()
    with _counts_lock:
        _counts.clear()


def describe():
    """Knobs + census (tools/diagnose.py "Tracing")."""
    return {"ring": _N, "enabled": enabled(), "spans": counts(),
            "retained": len(_ring), "last_dump": _last_dump}


def last_dump():
    """Path of the most recent :func:`dump` in this process, or None."""
    return _last_dump


# ------------------------------------------------------- chrome-trace dump --

def _span_event(rec, rank, offset, base_wall):
    ts = (rec["t0"] + offset - base_wall) * 1e6
    ev = {"name": rec["name"], "cat": f"trace.{rec['kind']}",
          "ph": "X", "pid": rank, "tid": rec.get("lane", 0),
          "ts": round(ts, 3), "dur": round(rec["dur_ms"] * 1e3, 3)}
    args = dict(rec.get("attrs") or {})
    if rec.get("trace"):
        args["trace"] = rec["trace"]
    if args:
        ev["args"] = args
    return ev


def _flight_event(rec, rank, offset, base_wall):
    ts = (rec["t_mono"] + offset - base_wall) * 1e6
    ev = {"name": rec["kind"], "cat": "flight", "ph": "i", "s": "p",
          "pid": rank, "tid": 0, "ts": round(ts, 3), "dur": 0}
    if rec.get("point") or rec.get("label") is not None:
        ev["args"] = {"point": rec.get("point"),
                      "label": rec.get("label")}
    return ev


def merged_events(shards):
    """Fold the rank shards' spans + flight tails into one list of
    chrome-trace events with per-rank lanes (``pid`` = rank) and clocks
    aligned via each shard's (t_wall, t_mono) heartbeat pair. Within a
    rank the alignment is a constant offset, so per-rank event order is
    preserved exactly (monotonicity test-asserted)."""
    return _merged(shards)[0]


def _merged(shards):
    lanes = []
    base_wall = None
    for rank in sorted(shards):
        sh = shards[rank]
        offset = float(sh["t_wall"]) - float(sh["t_mono"])
        spans = [r for r in sh.get("spans") or []
                 if isinstance(r, dict) and "t0" in r and "dur_ms" in r]
        flights = [r for r in sh.get("flight") or []
                   if isinstance(r, dict) and "t_mono" in r]
        for r in spans:
            wall = r["t0"] + offset
            base_wall = wall if base_wall is None else min(base_wall, wall)
        for r in flights:
            wall = r["t_mono"] + offset
            base_wall = wall if base_wall is None else min(base_wall, wall)
        lanes.append((rank, offset, spans, flights, sh))
    events = []
    if base_wall is None:
        base_wall = 0.0
    for rank, offset, spans, flights, sh in lanes:
        label = f"rank {rank}"
        if sh.get("generation"):
            label += f" (gen {sh['generation']})"
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0, "ts": 0, "dur": 0,
                       "cat": "__metadata", "args": {"name": label}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "tid": 0, "ts": 0, "dur": 0,
                       "cat": "__metadata", "args": {"sort_index": rank}})
        for r in spans:
            events.append(_span_event(r, rank, offset, base_wall))
        for r in flights:
            events.append(_flight_event(r, rank, offset, base_wall))
    return events, base_wall


def _local_shard():
    """This process's spans + flight tail shaped like a fleet shard (the
    single-process dump path)."""
    from . import flight as _flight

    rank, gen = coords()
    return rank, {"rank": rank, "generation": gen,
                  "t_wall": time.time(), "t_mono": time.monotonic(),
                  "spans": tail(), "flight": _flight.tail()}


def dump(path="trace.json", run_dir=None, include_profiler=True):
    """Write a merged Perfetto/chrome ``trace.json``.

    With ``run_dir`` (a gang run directory): fold EVERY rank's telemetry
    shard — spans, flight tails — into per-rank lanes, clock-aligned via
    the shards' heartbeat timestamps (torn/partial shards are skipped).
    Without it: this process's spans + flight tail, plus (when a
    profiler session recorded anything) the profiler's chrome events on
    the same timeline. Returns the written path."""
    global _last_dump
    if run_dir is not None:
        from . import fleet as _fleet

        shards = _fleet.read_shards(run_dir)
        rank, local = _local_shard()
        if local["spans"] and rank not in shards:
            shards[rank] = local
        events, _ = _merged(shards)
    else:
        rank, local = _local_shard()
        events, base_wall = _merged({rank: local})
        if include_profiler:
            offset = local["t_wall"] - local["t_mono"]
            events.extend(_profiler_events(rank, offset, base_wall))
    # profiler events recorded before the first span would land at a
    # negative timestamp; shift the whole timeline to start at 0
    neg = min((e["ts"] for e in events if e.get("ph") != "M"),
              default=0.0)
    if neg < 0:
        for e in events:
            if e.get("ph") != "M":
                e["ts"] = round(e["ts"] - neg, 3)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    _last_dump = os.path.abspath(path)
    return _last_dump


def _profiler_events(rank, offset, base_wall):
    """The profiler's recorded chrome events, re-based onto this dump's
    timeline (profiler timestamps are perf_counter-relative; its
    ``trace_info()`` carries the matching monotonic epoch)."""
    import sys

    prof = sys.modules.get("mxnet_tpu.profiler")
    if prof is None or not hasattr(prof, "trace_info"):
        return []
    info = prof.trace_info()
    epoch_mono = info["epoch_mono"]
    out = []
    for ev in info["events"]:
        ev = dict(ev)
        wall = epoch_mono + ev["ts"] / 1e6 + offset
        ev["ts"] = round((wall - base_wall) * 1e6, 3)
        ev["pid"] = rank
        out.append(ev)
    return out
