"""Metrics registry: counters / gauges / histograms with bounded label
sets, rendered as Prometheus text format and JSON.

The one export surface every subsystem publishes through. Two feeding
models, chosen per publisher so the hot paths stay untouched:

* **push** — coarse events update a metric directly at event time
  (trainer step gauges, kvstore op counters): one lock + dict probe,
  never on the per-op dispatch path;
* **pull** — subsystems that already keep their own counters
  (``compile.stats()``, ``serving.live_stats()``, the watchdog stall
  count, device memory) are read by *collectors*
  (:mod:`mxnet_tpu.telemetry.export`) at scrape time, so steady-state
  traffic pays nothing for being observable.

Cardinality is bounded by construction: each metric admits at most
``MXNET_TPU_TELEMETRY_MAX_SERIES`` (default 64) distinct label-value
combinations; further values collapse into an ``__other__`` series
instead of growing without bound (the classic metrics-OOM footgun).
"""
from __future__ import annotations

import os
import re
import threading

__all__ = ["counter", "gauge", "histogram", "get", "all_metrics",
           "snapshot", "render_prometheus", "reset",
           "DEFAULT_BUCKETS_MS"]

try:
    MAX_SERIES = int(os.environ.get("MXNET_TPU_TELEMETRY_MAX_SERIES", "64"))
except ValueError:
    MAX_SERIES = 64

# latency-flavoured default buckets (milliseconds)
DEFAULT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, float("inf"))

_lock = threading.Lock()
_METRICS: dict = {}   # name -> metric

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_OVERFLOW = "__other__"


def _sanitize(name):
    return _NAME_RE.sub("_", str(name))


class _Metric:
    kind = "untyped"

    def __init__(self, name, help="", labels=()):
        self.name = _sanitize(name)
        self.help = help
        self.labels = tuple(labels)
        self._lock = threading.Lock()
        self._series: dict = {}   # label-values tuple -> value

    def _key(self, label_values):
        if len(label_values) != len(self.labels):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labels}, got "
                f"{label_values!r}")
        key = tuple(str(v) for v in label_values)
        if key not in self._series and len(self._series) >= MAX_SERIES:
            key = (_OVERFLOW,) * len(self.labels)
        return key

    def series(self):
        with self._lock:
            return dict(self._series)

    def _snapshot_value(self, v):
        return v

    def snapshot(self):
        return {"kind": self.kind, "help": self.help,
                "labels": list(self.labels),
                "series": [{"labels": dict(zip(self.labels, k)),
                            "value": self._snapshot_value(v)}
                           for k, v in sorted(self.series().items())]}


class Counter(_Metric):
    """Monotone total. ``inc`` is the push path; ``set_total`` is the
    collector seam for totals owned by another subsystem (still rendered
    with TYPE counter — the value is a scrape of a monotone source)."""

    kind = "counter"

    def inc(self, amount=1.0, *label_values):
        key = self._key(label_values)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def set_total(self, value, *label_values):
        key = self._key(label_values)
        with self._lock:
            self._series[key] = float(value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, *label_values):
        key = self._key(label_values)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount=1.0, *label_values):
        key = self._key(label_values)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount=1.0, *label_values):
        self.inc(-amount, *label_values)


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=None):
        super().__init__(name, help, labels)
        bs = tuple(sorted(buckets or DEFAULT_BUCKETS_MS))
        if bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self.buckets = bs

    def observe(self, value, *label_values):
        key = self._key(label_values)
        with self._lock:
            rec = self._series.get(key)
            if rec is None:
                rec = self._series[key] = [0, 0.0,
                                           [0] * len(self.buckets)]
            rec[0] += 1
            rec[1] += float(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    rec[2][i] += 1

    def _snapshot_value(self, v):
        count, total, per = v
        return {"count": count, "sum": round(total, 6),
                "buckets": {("+Inf" if b == float("inf") else repr(b)): c
                            for b, c in zip(self.buckets, per)}}


def _get_or_create(cls, name, help, labels, **kw):
    name = _sanitize(name)
    with _lock:
        m = _METRICS.get(name)
        if m is None:
            m = _METRICS[name] = cls(name, help=help, labels=labels, **kw)
            return m
    if type(m) is not cls or m.labels != tuple(labels):
        raise ValueError(
            f"metric {name!r} already registered as {m.kind} with labels "
            f"{m.labels}, requested {cls.kind} with {tuple(labels)}")
    return m


def counter(name, help="", labels=()):
    """Get-or-create a :class:`Counter`."""
    return _get_or_create(Counter, name, help, labels)


def gauge(name, help="", labels=()):
    """Get-or-create a :class:`Gauge`."""
    return _get_or_create(Gauge, name, help, labels)


def histogram(name, help="", labels=(), buckets=None):
    """Get-or-create a :class:`Histogram`."""
    return _get_or_create(Histogram, name, help, labels, buckets=buckets)


def get(name):
    """The registered metric named `name`, or None."""
    return _METRICS.get(_sanitize(name))


def all_metrics():
    with _lock:
        return dict(_METRICS)


def reset():
    """Drop every registered metric (tests)."""
    with _lock:
        _METRICS.clear()


def snapshot():
    """JSON-able {name: {kind, help, labels, series}} of every metric.
    NOTE this is the *raw* registry — :func:`mxnet_tpu.telemetry.export.
    metrics_snapshot` runs the subsystem collectors first."""
    return {name: m.snapshot() for name, m in sorted(all_metrics().items())}


def _esc(v):
    return str(v).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _fmt(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _labelstr(names, values, extra=()):
    parts = [f'{n}="{_esc(v)}"' for n, v in zip(names, values)]
    parts += [f'{n}="{_esc(v)}"' for n, v in extra]
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus():
    """The registry in Prometheus text exposition format (0.0.4).
    Raw — the HTTP endpoints call :func:`mxnet_tpu.telemetry.export.
    render_prometheus`, which runs the collectors first."""
    lines = []
    for name, m in sorted(all_metrics().items()):
        if m.help:
            lines.append(f"# HELP {name} {m.help}")
        lines.append(f"# TYPE {name} {m.kind}")
        for key, v in sorted(m.series().items()):
            if m.kind == "histogram":
                count, total, per = v
                for b, c in zip(m.buckets, per):
                    lines.append(
                        f"{name}_bucket"
                        f"{_labelstr(m.labels, key, [('le', _fmt(b))])}"
                        f" {c}")
                lines.append(f"{name}_sum{_labelstr(m.labels, key)}"
                             f" {_fmt(total)}")
                lines.append(f"{name}_count{_labelstr(m.labels, key)}"
                             f" {count}")
            else:
                lines.append(f"{name}{_labelstr(m.labels, key)} {_fmt(v)}")
    return "\n".join(lines) + "\n"
