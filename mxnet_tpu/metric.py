"""Evaluation metrics.

Parity target: `python/mxnet/metric.py` (1829 LoC) — EvalMetric base with
registry/create, CompositeEvalMetric, Accuracy, TopKAccuracy, F1, MCC,
Perplexity, MAE, MSE, RMSE, CrossEntropy, NegativeLogLikelihood,
PearsonCorrelation, Loss, Torch, Caffe, CustomMetric + np/make helpers.
"""
from __future__ import annotations

import math

import numpy

from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
           "CustomMetric", "create", "np", "check_label_shapes"]

_registry = {}


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def alias(*aliases):
    def deco(klass):
        for a in aliases:
            _registry[a.lower()] = klass
        return klass

    return deco


def create(metric, *args, **kwargs):
    """parity: metric.py create — str name / callable / list."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, CompositeEvalMetric):
        return metric
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        if metric.lower() not in _registry:
            raise ValueError(f"metric {metric} is not registered; known: "
                             f"{sorted(_registry)}")
        return _registry[metric.lower()](*args, **kwargs)
    raise TypeError(f"cannot create metric from {metric!r}")


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """parity: metric.py check_label_shapes."""
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            f"Shape of labels {label_shape} does not match shape of "
            f"predictions {pred_shape}")
    if wrap:
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


def _as_numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


class EvalMetric:
    """Base metric (parity: metric.py:60)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        """Reset only the windowed (local) statistics, folding them into the
        epoch-global counters (parity: metric.py reset_local — used by
        Speedometer's auto_reset)."""
        self.global_sum_metric += self.sum_metric
        self.global_num_inst += self.num_inst
        self.num_inst = 0
        self.sum_metric = 0.0

    def _compute(self, total, num):
        """Value from accumulated (total, num) — overridden by metrics whose
        get() applies a transform (RMSE sqrt, Perplexity exp), so that
        get() and get_global() stay consistent."""
        return total / num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self._compute(self.sum_metric, self.num_inst))

    def get_global(self):
        """Epoch-global value including the current window (parity:
        metric.py get_global)."""
        num = getattr(self, "global_num_inst", 0) + self.num_inst
        total = getattr(self, "global_sum_metric", 0.0) + self.sum_metric
        if num == 0:
            return (self.name, float("nan"))
        return (self.name, self._compute(total, num))

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_global_name_value(self):
        name, value = self.get_global()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    """parity: metric.py CompositeEvalMetric."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def reset_local(self):
        for metric in getattr(self, "metrics", []):
            metric.reset_local()

    def _gather(self, getter):
        names, values = [], []
        for metric in self.metrics:
            name, value = getter(metric)
            if isinstance(name, str):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            names.extend(name)
            values.extend(value)
        return names, values

    def get(self):
        return self._gather(lambda m: m.get())

    def get_global(self):
        return self._gather(lambda m: m.get_global())


@register
@alias("acc")
class Accuracy(EvalMetric):
    """parity: metric.py Accuracy."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            # argmax whenever shapes differ (parity: Accuracy handles (N,1)
            # column labels vs (N,C) predictions)
            if pred.shape != label.shape:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            check_label_shapes(label, pred)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(pred)


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    """parity: metric.py TopKAccuracy."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, "Use Accuracy if top_k == 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype("int32")
            pred = numpy.argsort(_as_numpy(pred).astype("float32"), axis=-1)
            assert pred.ndim == 2, "Predictions should be 2 dims"
            num_samples, num_classes = pred.shape
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += (
                    pred[:, num_classes - 1 - j].flat ==
                    label.reshape(-1)).sum()
            self.num_inst += num_samples


class _BinaryClassificationHelper:
    """Confusion-matrix accumulator (parity: metric.py _BinaryClassificationMetrics)."""

    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.true_positives = 0
        self.false_positives = 0
        self.true_negatives = 0
        self.false_negatives = 0

    def update_binary_stats(self, label, pred):
        pred_label = numpy.argmax(pred, axis=1)
        check_label_shapes(label, pred_label)
        if len(numpy.unique(label)) > 2:
            raise ValueError("label must be binary")
        pred_true = pred_label == 1
        pred_false = ~pred_true
        label_true = label == 1
        label_false = ~label_true
        self.true_positives += (pred_true & label_true).sum()
        self.false_positives += (pred_true & label_false).sum()
        self.false_negatives += (pred_false & label_true).sum()
        self.true_negatives += (pred_false & label_false).sum()

    @property
    def precision(self):
        denom = self.true_positives + self.false_positives
        return self.true_positives / denom if denom > 0 else 0.0

    @property
    def recall(self):
        denom = self.true_positives + self.false_negatives
        return self.true_positives / denom if denom > 0 else 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (self.precision + self.recall)
        return 0.0

    @property
    def matthewscc(self):
        terms = [(self.true_positives + self.false_positives),
                 (self.true_positives + self.false_negatives),
                 (self.true_negatives + self.false_positives),
                 (self.true_negatives + self.false_negatives)]
        denom = 1.0
        for t in terms:
            denom *= max(float(t), 1.0)
        return ((self.true_positives * self.true_negatives
                 - self.false_positives * self.false_negatives)
                / math.sqrt(denom))

    @property
    def total_examples(self):
        return (self.true_positives + self.false_positives
                + self.true_negatives + self.false_negatives)

    def absorb(self, other):
        """Fold another accumulator's counts into this one, resetting it
        (used by reset_local to bank the window into the epoch-global)."""
        self.true_positives += other.true_positives
        self.false_positives += other.false_positives
        self.true_negatives += other.true_negatives
        self.false_negatives += other.false_negatives
        other.reset_stats()

    def combined(self, other):
        c = _BinaryClassificationHelper()
        c.true_positives = self.true_positives + other.true_positives
        c.false_positives = self.false_positives + other.false_positives
        c.true_negatives = self.true_negatives + other.true_negatives
        c.false_negatives = self.false_negatives + other.false_negatives
        return c


@register
class F1(EvalMetric):
    """parity: metric.py F1 (average='macro'|'micro')."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationHelper()
        self.global_metrics = _BinaryClassificationHelper()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_as_numpy(label).astype("int32"),
                                             _as_numpy(pred))
            if self.average == "macro":
                self.sum_metric += self.metrics.fscore
                self.num_inst += 1
                self.metrics.reset_stats()

    def get(self):
        if self.average == "micro":
            if self.metrics.total_examples == 0:
                return (self.name, float("nan"))
            return (self.name, self.metrics.fscore)
        return super().get()

    def get_global(self):
        if self.average == "micro":
            comb = self.global_metrics.combined(self.metrics)
            if comb.total_examples == 0:
                return (self.name, float("nan"))
            return (self.name, comb.fscore)
        return super().get_global()

    def reset(self):
        super().reset()
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()
            self.global_metrics.reset_stats()

    def reset_local(self):
        super().reset_local()
        if hasattr(self, "metrics"):
            self.global_metrics.absorb(self.metrics)


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (parity: metric.py MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationHelper()
        self.global_metrics = _BinaryClassificationHelper()
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_as_numpy(label).astype("int32"),
                                             _as_numpy(pred))
            if self.average == "macro":
                self.sum_metric += self.metrics.matthewscc
                self.num_inst += 1
                self.metrics.reset_stats()

    def get(self):
        if self.average == "micro":
            if self.metrics.total_examples == 0:
                return (self.name, float("nan"))
            return (self.name, self.metrics.matthewscc)
        return super().get()

    def get_global(self):
        if self.average == "micro":
            comb = self.global_metrics.combined(self.metrics)
            if comb.total_examples == 0:
                return (self.name, float("nan"))
            return (self.name, comb.matthewscc)
        return super().get_global()

    def reset(self):
        super().reset()
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()
            self.global_metrics.reset_stats()

    def reset_local(self):
        super().reset_local()
        if hasattr(self, "metrics"):
            self.global_metrics.absorb(self.metrics)


@register
class Perplexity(EvalMetric):
    """parity: metric.py Perplexity."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if self.axis not in (-1, pred.ndim - 1):
                pred = numpy.moveaxis(pred, self.axis, -1)
            label = label.reshape(-1).astype("int64")
            probs = pred.reshape(-1, pred.shape[-1])[
                numpy.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = numpy.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= numpy.log(numpy.maximum(1e-10, probs)).sum()
            num += label.size
        self.sum_metric += loss
        self.num_inst += num

    def _compute(self, total, num):
        return math.exp(total / num)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def _compute(self, total, num):
        return math.sqrt(total / num)


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    """parity: metric.py CrossEntropy."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
@alias("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples
            prob = pred[numpy.arange(num_examples), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += num_examples


@register
@alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            check_label_shapes(label, pred)
            self.sum_metric += numpy.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of raw loss values (parity: metric.py Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            arr = _as_numpy(pred)
            self.sum_metric += arr.sum()
            self.num_inst += arr.size


@register
class CustomMetric(EvalMetric):
    """parity: metric.py CustomMetric — wrap feval(label, pred)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, wrap=True)
        else:
            if isinstance(labels, NDArray):
                labels = [labels]
            if isinstance(preds, NDArray):
                preds = [preds]
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """parity: metric.py np — create a CustomMetric from a numpy function."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
