"""Imperative autograd: tape recording + reverse pass.

Parity target: `src/imperative/imperative.cc` + `python/mxnet/autograd.py` —
`record()/pause()` TLS flags (`imperative.h:102`), per-op tape recording
(`Imperative::RecordOp` :193 stamping AGInfo on nnvm nodes), and
`Imperative::Backward` :280 (prune unreachable, run Gradient pass, execute).

TPU-native redesign: instead of re-deriving gradients from per-op FGradient
registrations at backward time, the tape captures a ``jax.vjp`` closure at
*forward* time (the pullback holds exactly the residuals XLA decides to
keep). Backward is then a pure tape walk: reverse-topological cotangent
accumulation into leaf ``.grad`` buffers. Exceptions raised inside vjp
executables surface at the `backward()` sync point, matching the engine's
deferred-error semantics.

Hybridized blocks record ONE tape node for their whole compiled call —
identical to CachedOp recording a single node (`cached_op.cc:762`).
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = [
    "record", "pause", "train_mode", "predict_mode", "is_recording",
    "is_training", "set_recording", "set_training", "mark_variables",
    "backward", "grad", "get_symbol", "Function",
]

_tls = threading.local()


def _flags():
    if not hasattr(_tls, "recording"):
        _tls.recording = False
        _tls.training = False
    return _tls


def is_recording() -> bool:
    return _flags().recording


def is_training() -> bool:
    return _flags().training


def set_recording(is_record: bool) -> bool:
    f = _flags()
    prev, f.recording = f.recording, is_record
    if prev != is_record:
        # recording-state flips are bulking sync points: a segment opened
        # under one autograd state must not absorb ops from the other
        from . import bulk

        bulk.flush()
    return prev


def set_training(train: bool) -> bool:
    f = _flags()
    prev, f.training = f.training, train
    return prev


class _RecordingStateScope:
    """parity: python/mxnet/autograd.py:35-75."""

    def __init__(self, is_record: Optional[bool], train_mode_: Optional[bool]):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode: bool = True):
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------- tape -----

LEAF, NODE, CONST = 0, 1, 2


class TapeNode:
    """One recorded op application (parity: nnvm node + AGInfo,
    `include/mxnet/imperative.h:53-92`).

    `fwd_fn` (the pure op emitter with static kwargs bound) enables tape
    REPLAY as a pure function of chosen leaves — the machinery behind
    `grad(create_graph=True)` (higher-order gradients via composed
    jax.vjp). Nodes that cannot be replayed (custom Functions) leave it
    None."""

    __slots__ = ("op_name", "vjp_fn", "entries", "num_outputs", "out_shapes",
                 "out_dtypes", "fwd_fn")

    def __init__(self, op_name, vjp_fn, entries, num_outputs, out_shapes,
                 out_dtypes, fwd_fn=None):
        self.op_name = op_name
        self.vjp_fn = vjp_fn  # pullback: cotangents -> input cotangents
        self.entries = entries  # [(kind, ndarray_or_node, out_idx)]
        self.num_outputs = num_outputs
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.fwd_fn = fwd_fn


def make_entries(nd_inputs):
    """Classify each input for the tape: leaf (has grad buffer), node output,
    or constant (the constant keeps its array ref for tape replay)."""
    entries = []
    for x in nd_inputs:
        node = getattr(x, "_tape_node", None)
        if node is not None:
            entries.append((NODE, node, x._tape_index))
        elif getattr(x, "_grad_req", "null") != "null":
            entries.append((LEAF, x, 0))
        else:
            entries.append((CONST, x, 0))
    return entries


def any_on_tape(nd_inputs) -> bool:
    for x in nd_inputs:
        if getattr(x, "_tape_node", None) is not None:
            return True
        if getattr(x, "_grad_req", "null") != "null":
            return True
    return False


def mark_variables(variables, gradients, grad_reqs="write"):
    """parity: MXAutogradMarkVariables — attach grad buffers to arrays."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad_req = req
        var._grad = g
        var._tape_node = None
        var._tape_index = 0


def _toposort(heads: List[TapeNode]):
    """Reverse-topological order over reachable tape nodes (parity:
    Imperative::Backward's reachability prune, imperative.cc:147)."""
    order, state = [], {}
    stack = [(n, False) for n in heads]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if state.get(id(node)):
            continue
        state[id(node)] = True
        stack.append((node, True))
        for kind, ref, _ in node.entries:
            if kind == NODE and not state.get(id(ref)):
                stack.append((ref, False))
    return order[::-1]  # heads-first


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the reverse pass from `heads`, accumulating into leaf `.grad`.

    parity: MXAutogradBackwardEx -> Imperative::Backward (imperative.cc:280).
    """
    import jax.numpy as jnp

    from . import bulk
    from .ndarray import NDArray

    # backward is a sync point: pending bulk segments must execute (and
    # stamp their per-segment tape nodes) before the tape walk
    bulk.flush()

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # seed cotangents
    cot = {}  # id(node) -> [cotangent per output]
    written = set()  # leaves already written this backward (for 'write' req)
    root_nodes = []
    for h, hg in zip(heads, head_grads):
        node = h._tape_node
        if node is None:
            if h._grad_req != "null":
                # head is itself a leaf: d head / d head = 1
                seed = jnp.ones_like(h._data) if hg is None else hg._data
                _accumulate_leaf(h, seed, written)
                continue
            raise ValueError("cannot differentiate a head that is not on the "
                             "autograd tape (did you forget autograd.record()?)")
        root_nodes.append(node)
        slot = cot.setdefault(id(node), [None] * node.num_outputs)
        seed = jnp.ones(node.out_shapes[h._tape_index],
                        node.out_dtypes[h._tape_index]) if hg is None else hg._data
        slot[h._tape_index] = seed if slot[h._tape_index] is None \
            else slot[h._tape_index] + seed

    order = _toposort(root_nodes)
    for node in order:
        cots = cot.pop(id(node), None)
        if cots is None:
            continue
        full = []
        for i, c in enumerate(cots):
            if c is None:
                c = jnp.zeros(node.out_shapes[i], node.out_dtypes[i])
            elif c.dtype != node.out_dtypes[i]:
                # mixed-precision graphs (AMP): downstream vjps may hand
                # back a wider cotangent than this node's output dtype
                c = c.astype(node.out_dtypes[i])
            full.append(c)
        full = tuple(full)
        in_cots = node.vjp_fn(full if node.num_outputs > 1 else full[0])
        if not retain_graph:
            node.vjp_fn = None  # free residuals eagerly
        for (kind, ref, idx), g in zip(node.entries, in_cots):
            if g is None or _is_float0(g):
                continue
            if kind == LEAF:
                _accumulate_leaf(ref, g, written)
            elif kind == NODE:
                slot = cot.setdefault(id(ref), [None] * ref.num_outputs)
                slot[idx] = g if slot[idx] is None else slot[idx] + g

    if not retain_graph:
        for h in heads:
            h._tape_node = None


def _is_float0(g):
    import jax

    return getattr(g, "dtype", None) == jax.dtypes.float0


def _accumulate_leaf(leaf, g, written):
    req = leaf._grad_req
    if req == "null" or leaf._grad is None:
        return
    leaf._fresh_grad = True  # stale-grad tracking (parity: Parameter._fresh_grad)
    g = g.astype(leaf._grad._data.dtype)
    if req == "write" and id(leaf) not in written:
        # 'write': first contribution this backward overwrites; further
        # contributions (multiple tape paths) sum, matching kWriteTo + kAddTo
        # within one grad graph in the reference.
        leaf._grad._data = g
        written.add(id(leaf))
    else:
        leaf._grad._data = leaf._grad._data + g
        written.add(id(leaf))


def _build_replay(heads, variables):
    """Reconstruct the recorded computation as a pure function
    leaf_raws -> head_raws by walking the tape with each node's stored
    `fwd_fn`. Replay is what makes higher-order grads exact: re-deriving
    through jax.vjp-of-replay sees the residuals' dependence on the
    leaves, which the first-order pullbacks (closed over constant
    residuals) cannot."""
    roots = [h._tape_node for h in heads if h._tape_node is not None]
    order = _toposort(roots)[::-1]  # leaves-first for forward replay
    for node in order:
        if node.fwd_fn is None:
            raise NotImplementedError(
                f"create_graph=True cannot replay node {node.op_name!r} "
                "(hybridized/custom-Function nodes record no forward fn); "
                "run the forward un-hybridized")
    leaf_pos = {id(v): i for i, v in enumerate(variables)}

    def replay(*leaf_raws):
        vals = {}

        def value_of(entry):
            kind, ref, idx = entry
            if kind == NODE:
                return vals[id(ref)][idx]
            pos = leaf_pos.get(id(ref))
            if pos is not None:
                return leaf_raws[pos]
            return ref._data  # other leaf / constant: current value

        for node in order:
            ins = [value_of(e) for e in node.entries]
            outs = node.fwd_fn(*ins)
            vals[id(node)] = outs if isinstance(outs, tuple) else (outs,)

        head_raws = []
        for h in heads:
            if h._tape_node is None:
                pos = leaf_pos.get(id(h))
                head_raws.append(leaf_raws[pos] if pos is not None
                                 else h._data)
            else:
                head_raws.append(vals[id(h._tape_node)][h._tape_index])
        return tuple(head_raws)

    return replay


def _grad_create_graph(heads, variables, head_grads):
    """grad() with create_graph=True: differentiate the tape REPLAY inside
    a recorded call, so the returned gradients are themselves on the tape
    (second backward composes jax.vjp twice)."""
    import jax
    import jax.numpy as jnp

    from .ndarray import _invoke_fn

    for v in variables:
        if v._grad_req == "null" or v._grad is None:
            raise ValueError("variables passed to autograd.grad must have "
                             "attach_grad() called (be tape leaves)")
    replay = _build_replay(heads, variables)
    if head_grads is None:
        head_grads = [None] * len(heads)
    cots = tuple(
        jnp.ones(h.shape, h._data.dtype) if g is None
        else g._data.reshape(h.shape).astype(h._data.dtype)
        for h, g in zip(heads, head_grads))

    def g(*leaf_raws):
        _, pull = jax.vjp(replay, *leaf_raws)
        grads = pull(cots)
        # single-variable: bare output so the tape's single-cotangent
        # convention matches the pullback structure
        return grads if len(grads) > 1 else grads[0]

    out = _invoke_fn(g, "grad", list(variables), {})
    return list(out) if isinstance(out, tuple) else [out]


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Compute and *return* gradients of heads w.r.t. variables.

    parity: python/mxnet/autograd.py:271. ``create_graph=True`` replays
    the tape as a pure function and differentiates it under recording, so
    the result supports further `backward()`/`grad()` calls.
    """
    from . import bulk
    from .ndarray import NDArray

    bulk.flush()  # sync point: segments stamp tape nodes before the walk

    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    if head_grads is not None and isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if create_graph:
        return _grad_create_graph(heads, variables, head_grads)
    saved = [(v._grad_req, v._grad) for v in variables]
    from .ndarray import zeros_like

    for v in variables:
        if v._grad_req == "null" or v._grad is None:
            raise ValueError("variables passed to autograd.grad must have "
                             "attach_grad() called (be tape leaves)")
        v._grad = zeros_like(v)
        v._grad_req = "add"
    backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
    out = [v._grad for v in variables]
    for v, (req, g) in zip(variables, saved):
        v._grad_req, v._grad = req, g
    return out


def get_symbol(x):
    raise NotImplementedError(
        "autograd.get_symbol: the imperative tape does not materialise a "
        "Symbol; use mxnet_tpu.symbol tracing instead")


class Function:
    """Custom differentiable function (parity: mx.autograd.Function,
    python/mxnet/autograd.py:368).

    Subclass and implement ``forward`` and ``backward`` using NDArrays. The
    pair is recorded as one tape node whose pullback calls ``backward``.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording() and any_on_tape(inputs):
            entries = make_entries(inputs)

            def vjp_fn(cots):
                cots = (cots,) if single or not isinstance(cots, tuple) else cots
                with pause():
                    in_grads = self.backward(*[NDArray(c) for c in cots])
                if isinstance(in_grads, NDArray):
                    in_grads = (in_grads,)
                return tuple(g._data if g is not None else None for g in in_grads)

            node = TapeNode(type(self).__name__, vjp_fn, entries, len(outs),
                            [o.shape for o in outs], [o._data.dtype for o in outs])
            for i, o in enumerate(outs):
                o._tape_node = node
                o._tape_index = i
        return outputs
