"""``mx.library`` — load external operator libraries at runtime.

Parity: ``python/mxnet/library.py`` (``load`` → ``MXLoadLib``) and the
extension framework ``include/mxnet/lib_api.h`` (custom ops / passes /
partitioners compiled into a standalone ``.so`` and registered at load
time, demoed in ``example/extensions/lib_custom_op``).

TPU-native redesign: the reference's lib_api ships a 4k-line header whose
custom ops implement CPU/GPU kernels and get woven into the NNVM graph.
On TPU the compute graph belongs to XLA, so an extension library exposes a
small C ABI (below) and each exported op is registered as a JAX op whose
body is a :func:`jax.pure_callback` into the library's kernel — the same
mechanism the ``Custom`` python op uses, so loaded ops work eagerly, in
``hybridize``d blocks and in Symbol graphs. Python extension files
(``.py``) are also accepted: they are exec'd and may register ops via
``mx.operator.register`` or ``mxnet_tpu.ops.registry.register``.

Required C ABI for a ``.so`` extension (see
``examples/extensions/lib_custom_op/`` for a complete sample)::

    int         mxtpu_lib_version(void);           // must return 1
    int         mxtpu_lib_num_ops(void);
    const char *mxtpu_lib_op_name(int op_idx);
    // dtype codes: 0=float32 1=float64 2=int32 3=int64
    int mxtpu_lib_op_infer_shape(int op_idx, int num_in,
                                 const int64_t **in_shapes,
                                 const int *in_ndims,
                                 int64_t *out_shape /* cap 8 */,
                                 int *out_ndim);
    int mxtpu_lib_op_forward(int op_idx, int num_in,
                             const void **in, const int64_t **in_shapes,
                             const int *in_ndims, int dtype,
                             void *out, const int64_t *out_shape,
                             int out_ndim);

All entry points return 0 on success. Kernels run on host memory (XLA
stages the callback around device execution); gradients are not provided —
loaded ops register as non-differentiable, matching reference extension
ops that omit a backward.
"""
from __future__ import annotations

import ctypes
import functools
import os
import runpy

import numpy as np

__all__ = ["load", "loaded_libraries"]

_DTYPE_CODES = {np.dtype("float32"): 0, np.dtype("float64"): 1,
                np.dtype("int32"): 2, np.dtype("int64"): 3}
_MAX_NDIM = 8

_LOADED = {}


def loaded_libraries():
    """Paths of every library loaded so far this process."""
    return list(_LOADED)


def _shape_args(shapes_in):
    shapes = [(ctypes.c_int64 * len(s))(*s) for s in shapes_in]
    shape_ptrs = (ctypes.POINTER(ctypes.c_int64) * len(shapes_in))(
        *[ctypes.cast(s, ctypes.POINTER(ctypes.c_int64)) for s in shapes])
    ndims = (ctypes.c_int * len(shapes_in))(*[len(s) for s in shapes_in])
    return shapes, shape_ptrs, ndims


def _infer_shape(lib, idx, in_shapes):
    _keep, shape_ptrs, ndims = _shape_args(in_shapes)
    out_shape = (ctypes.c_int64 * _MAX_NDIM)()
    out_ndim = ctypes.c_int()
    rc = lib.mxtpu_lib_op_infer_shape(idx, len(in_shapes), shape_ptrs, ndims,
                                      out_shape, ctypes.byref(out_ndim))
    if rc != 0:
        raise RuntimeError(f"extension infer_shape failed with code {rc}")
    return tuple(out_shape[i] for i in range(out_ndim.value))


def _host_call(lib, idx, out_shape, out_dtype, *np_in):
    np_in = [np.ascontiguousarray(a) for a in np_in]
    code = _DTYPE_CODES[np.dtype(out_dtype)]
    _keep, shape_ptrs, ndims = _shape_args([a.shape for a in np_in])
    in_ptrs = (ctypes.c_void_p * len(np_in))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in np_in])
    out = np.empty(out_shape, out_dtype)
    oshape = (ctypes.c_int64 * len(out_shape))(*out_shape)
    rc = lib.mxtpu_lib_op_forward(
        idx, len(np_in), in_ptrs, shape_ptrs, ndims, code,
        out.ctypes.data_as(ctypes.c_void_p), oshape, len(out_shape))
    if rc != 0:
        raise RuntimeError(f"extension op forward failed with code {rc}")
    return out


def _register_lib_op(lib, idx, name, verbose):
    import jax

    from .ops import registry

    def ext_op(*arrays, **kwargs):
        if kwargs:
            raise TypeError(f"extension op {name!r} takes no keyword args")
        dt = np.dtype(arrays[0].dtype)
        if dt not in _DTYPE_CODES:
            raise TypeError(f"extension op {name!r}: unsupported dtype {dt}")
        out_shape = _infer_shape(lib, idx, [tuple(a.shape) for a in arrays])
        return jax.pure_callback(
            functools.partial(_host_call, lib, idx, out_shape, dt),
            jax.ShapeDtypeStruct(out_shape, dt), *arrays)

    ext_op.__name__ = name
    ext_op.__doc__ = f"extension op {name!r} loaded via mx.library.load"
    registry.register(name, differentiable=False, eager=True)(ext_op)
    _expose_ops([name])
    if verbose:
        import logging

        logging.getLogger("mxnet_tpu").info("loaded extension op %s", name)


def _expose_ops(names):
    """Add mx.nd.<name> / mx.sym.<name> wrappers for ops registered after
    import time (the import-time wrapper loops have already run)."""
    import sys

    for mod_name in ("mxnet_tpu.ndarray", "mxnet_tpu.symbol"):
        mod = sys.modules.get(mod_name)
        if mod is None:
            continue
        for name in names:
            if not hasattr(mod, name):
                setattr(mod, name, mod._make_wrapper(name))


def load(path, verbose=True):
    """Load an extension library (parity: python/mxnet/library.py:32
    ``load`` → ``MXLoadLib`` → ``c_api.cc:1536``).

    ``path`` may be a compiled ``.so`` implementing the mxtpu extension ABI
    (ops are registered under their exported names) or a ``.py`` file that
    registers ops itself when executed."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise ValueError(f"library {path!r} does not exist")
    if path in _LOADED:
        return _LOADED[path]
    if path.endswith(".py"):
        from .ops import registry

        before = set(registry.list_ops())
        ns = runpy.run_path(path)
        _expose_ops(sorted(set(registry.list_ops()) - before))
        _LOADED[path] = ns
        return ns
    lib = ctypes.CDLL(path)
    for sym in ("mxtpu_lib_version", "mxtpu_lib_num_ops", "mxtpu_lib_op_name",
                "mxtpu_lib_op_infer_shape", "mxtpu_lib_op_forward"):
        if not hasattr(lib, sym):
            raise ValueError(
                f"{path!r} is not an mxtpu extension library (missing {sym})")
    lib.mxtpu_lib_op_name.restype = ctypes.c_char_p
    version = lib.mxtpu_lib_version()
    if version != 1:
        raise ValueError(f"extension ABI version {version} unsupported")
    names = []
    for idx in range(lib.mxtpu_lib_num_ops()):
        name = lib.mxtpu_lib_op_name(idx).decode()
        _register_lib_op(lib, idx, name, verbose)
        names.append(name)
    _LOADED[path] = {"handle": lib, "ops": names}
    return _LOADED[path]
