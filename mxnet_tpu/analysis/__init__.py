"""mxnet_tpu.analysis — the static-analysis subsystem.

The NNVM-pass analogue for this reproduction, TPU-flavored:

* :mod:`~mxnet_tpu.analysis.verify` — graph verifier passes over the
  Symbol node DAG (kwargs vs OpSchema, shape/dtype inference consistency,
  dangling/duplicate inputs, cycles, dead outputs). ``Symbol.verify()`` /
  automatic inside ``simple_bind`` (``MXNET_TPU_VERIFY=0`` opts out).
* :mod:`~mxnet_tpu.analysis.sanitize` — runtime sync-hazard sanitizer
  layered on the bulking engine (``MXNET_TPU_SANITIZE=1``).
* :mod:`~mxnet_tpu.analysis.distcheck` — distributed-correctness analyzer
  over the parallel layer (sharding verifier, collective-order deadlock
  detector, donation-safety checker, recompile-churn detector). The module
  is callable: ``analysis.distcheck(...)``; auto-run by ``ShardedTrainer``
  before compile unless ``MXNET_TPU_DISTCHECK=0``.
* :mod:`~mxnet_tpu.analysis.concur` — concurrency analyzer over the
  threaded control plane (lock-order deadlock detector, shared-state
  pass, torn-file protocol checker, runtime lock witness). Callable:
  ``analysis.concur(...)``; ``MXNET_TPU_CONCUR=0`` opts out and
  ``MXNET_TPU_CONCUR_TRACE=1`` arms the witness at import.

The companion source-level checker lives in ``tools/mxlint.py`` (which
runs concur's static passes as its three concurrency rules).

``sanitize`` and ``distcheck`` are imported eagerly (NDArray sync points
and the dispatch/compile caches read their ``ACTIVE``/``DONATED``/
``CACHE_TRACK`` flags inline); the verifier — which pulls in the
symbol/registry layers — and the concurrency analyzer load on first use.
"""
from __future__ import annotations

from . import distcheck, sanitize

__all__ = ["sanitize", "distcheck", "concur", "verify", "verify_graph",
           "GraphVerifyError", "Issue", "raise_if_errors", "verify_enabled"]

_VERIFY_NAMES = ("verify_graph", "GraphVerifyError", "Issue",
                 "raise_if_errors", "verify_enabled", "node_failure_message")


def __getattr__(name):
    # import_module, NOT `from . import x`: the fromlist form re-enters
    # this __getattr__ through importlib's hasattr probe before the
    # submodule attribute is bound — unbounded recursion
    if name == "verify" or name in _VERIFY_NAMES:
        import importlib

        _verify = importlib.import_module(".verify", __name__)
        globals().setdefault("verify", _verify)
        if name == "verify":
            return _verify
        value = getattr(_verify, name)
        globals()[name] = value
        return value
    if name == "concur":
        import importlib

        _concur = importlib.import_module(".concur", __name__)
        globals()["concur"] = _concur
        return _concur
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
