"""Concurrency analyzer: lock-order, shared-state, torn-file + witness.

The fourth analysis subsystem. The reference's threaded engine made every
dependency hazard explicit — ``Engine::PushAsync`` declared the vars an
operation read and mutated, and the engine scheduled around them (SURVEY
§L2). This reproduction's Python control plane (serving batcher threads,
watchdog waiters, gang heartbeat daemons, bus watchers, fleet routers)
has no such declaration layer, and each of the last three PRs found a
real concurrency bug only en route. This module turns that class of bug
into a checked contract, in four passes:

* **Pass 1 — lock-order deadlock detector** (:func:`check_lock_order`):
  AST walk over the package extracting every ``threading.Lock`` /
  ``RLock`` / ``Condition`` attribute and the ``with``/``acquire``
  nesting between them per function (following direct intra-package
  calls one level deep), building a global lock-acquisition graph. A
  cycle is a potential-deadlock Issue naming both acquisition sites.
* **Pass 2 — shared-state pass** (:func:`check_shared_state`): flag
  module-level mutable globals and ``self.*`` containers *written* from
  code reachable from a ``Thread(target=...)``/``Timer`` entry point
  while also written from non-thread code, with no common lock held at
  every write site — the exact class of the ``_atomic_json`` bug (PR
  16). Known-safe idioms (seq-claimed flight ring slots, warn-once
  latches, lossy counters) carry a ``# concur: atomic`` suppression.
* **Pass 3 — torn-file protocol checker** (:func:`check_torn_files`):
  every ``open(..., "w")`` / ``json.dump`` / ``os.replace`` site must
  route through ``checkpoint.atomic_write`` (a writer callback, checked
  by line interval) or a seam registered in :data:`TORN_SEAMS`; seam
  functions doing their own tmp+replace must embed **pid and thread
  ident** in the tmp name; ``json.load`` readers of the on-disk JSON
  protocols must tolerate torn records (skip-on-parse-error visible in
  the same function). ``# concur: torn-ok`` suppresses a site.
* **Pass 4 — runtime lock witness** (:func:`trace_locks` /
  :func:`check_witness`): an opt-in shim wrapping the package's
  module-level locks to record the *actual* acquisition order per
  thread in a constant-memory flight-style ring. The witnessed order is
  cross-checked against the static graph (and against itself) on demand
  or at process exit — a witnessed inversion raises a site-named
  :class:`LockOrderError` in tests/chaos instead of a silent future
  deadlock.

Findings are structured :class:`Issue` objects (same shape as the graph
verifier's); errors raise :class:`ConcurError` (a ``GraphVerifyError``
subclass when the package is importable). The module is callable —
``mxnet_tpu.analysis.concur(...)`` is :func:`run` — and the whole
subsystem honours ``MXNET_TPU_CONCUR=0``.

This file is deliberately **stdlib-only at import time** so
``tools/mxlint.py`` can load it standalone (by file path) and run passes
1–3 as lint rules without importing the jax-heavy package.
"""
from __future__ import annotations

import ast
import itertools
import os
import sys
import threading
import time
import types

__all__ = [
    "enabled", "run", "run_static", "scan", "Issue",
    "ConcurWarning", "LockOrderError",
    "check_lock_order", "check_shared_state", "check_torn_files",
    "TORN_SEAMS", "register_seam",
    "trace_locks", "untrace_locks", "wrap", "check_witness",
    "witness_state", "witness_tail", "reset_witness",
]

ENV = "MXNET_TPU_CONCUR"
ENV_TRACE = "MXNET_TPU_CONCUR_TRACE"
ENV_RING = "MXNET_TPU_CONCUR_RING"

_LOCK_KINDS = ("Lock", "RLock", "Condition")
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popitem", "remove", "discard",
    "clear", "sort", "reverse",
})
_MUTABLE_CTORS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray",
})
# exception names broad enough to swallow a torn/partial JSON record
_TORN_GUARDS = frozenset({
    "ValueError", "JSONDecodeError", "Exception", "BaseException",
})


def enabled() -> bool:
    """The ``MXNET_TPU_CONCUR`` gate (on unless explicitly disabled):
    controls :func:`run`, the mxlint concurrency rules, and the lock
    witness arming."""
    return os.environ.get(ENV, "1").lower() not in ("0", "false", "off")


def _package_root():
    # mxnet_tpu/analysis/concur.py -> mxnet_tpu/
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ====================================================================== #
# Structured findings                                                    #
# ====================================================================== #

class Issue:
    """One concurrency finding. Same field shape as the graph verifier's
    ``Issue`` (severity/code/node/op/message), with ``node`` carrying the
    ``path:line`` site and ``op`` the enclosing function's qualname —
    kept local so this module loads without the package."""

    __slots__ = ("severity", "code", "node", "op", "message")

    def __init__(self, severity, code, node, op, message):
        self.severity = severity  # "error" | "warning"
        self.code = code
        self.node = node          # "relpath.py:line" site
        self.op = op              # enclosing function qualname ("" = module)
        self.message = message

    @property
    def is_error(self):
        return self.severity == "error"

    def __str__(self):
        where = self.node or "package"
        if self.op:
            where += f" ({self.op})"
        return f"[{self.severity}:{self.code}] {where}: {self.message}"

    def __repr__(self):
        return f"<Issue {self}>"


class ConcurWarning(UserWarning):
    """Warning-severity concurrency findings surface here."""


class LockOrderError(RuntimeError):
    """A witnessed lock-acquisition order contradicts the established
    order (static graph or an earlier witnessed pair); the message names
    both acquisition sites and the offending thread."""


def _realise_error_class():
    """``ConcurError`` subclasses ``GraphVerifyError`` (same structured
    ``.issues`` payload) when the package is importable; standalone (the
    mxlint file-path load) it falls back to a plain RuntimeError
    subclass so passes 1–3 still run without jax on the path."""
    try:
        from .verify import GraphVerifyError as _Base  # type: ignore
    except Exception:
        class _Base(RuntimeError):  # type: ignore
            def __init__(self, issues):
                self.issues = list(issues)
                errors = [i for i in self.issues if i.is_error]
                lines = "\n  ".join(str(i) for i in errors)
                super().__init__(
                    f"concurrency verification failed ({len(errors)} "
                    f"error{'s' if len(errors) != 1 else ''}):\n  {lines}")

    class ConcurError(_Base):
        """Concurrency verification failed; ``.issues`` carries the
        structured finding list (errors + warnings)."""

    ConcurError.__module__ = __name__
    return ConcurError


def _raise_if_errors(issues, warn=True):
    import warnings

    if warn:
        for i in issues:
            if not i.is_error:
                warnings.warn(str(i), ConcurWarning, stacklevel=3)
    if any(i.is_error for i in issues):
        raise sys.modules[__name__].ConcurError(issues)
    return issues


# ====================================================================== #
# Torn-file seam registry (pass 3)                                       #
# ====================================================================== #

# (modkey, qualname) -> reason. A seam is a function allowed to touch
# the filesystem write path directly; everything else must route through
# checkpoint.atomic_write (or carry `# concur: torn-ok`). Seams that do
# their own tmp+os.replace are additionally held to the pid+thread-ident
# tmp-name rule (the PR 16 `_atomic_json` bug class).
TORN_SEAMS = {
    ("checkpoint", "atomic_write"):
        "the canonical tmp+fsync+replace seam every protocol writer uses",
    ("elastic", "_atomic_json"):
        "heartbeat/announce writer kept off atomic_write so beats stay "
        "recordable while the ckpt.write fault point is armed",
    ("cluster", "atomic_record"):
        "world-state/spec writer — the supervisor must stay crash-safe "
        "while the ckpt.write fault point is armed, so it owns its seam",
    ("telemetry.fleet", "_atomic_json"):
        "telemetry shard writer — same fault-isolation contract as "
        "elastic's",
    ("serving.worker", "write_spec"):
        "serving.json author (test/tooling side, pre-fleet)",
    ("kernels.table", "save"):
        "dispatch-table snapshot with its own pid+tid tmp+fsync+replace",
    ("compile", "_atomic_write_bytes"):
        "compile-cache writer: atomic_write's local twin without the "
        "ckpt.write fault point (PR 15 framed entries)",
    ("watchdog", "_write_bundle"):
        "crash-bundle writer: bundle dir is uniquely named per "
        "pid+seq, single-writer by construction",
    ("watchdog", "_dump_tracebacks"):
        "crash-bundle helper — writes inside the single-writer bundle "
        "dir",
    ("recordio", "MXRecordIO.open"):
        "recordio data file — single-writer file format by contract",
    ("recordio", "MXIndexedRecordIO.open"):
        "recordio index file — single-writer file format by contract",
    # user-facing save APIs: caller-named destination paths, single
    # writer by MXNet API contract (parity surface — a torn file on
    # crash mirrors the reference's semantics)
    ("symbol.symbol", "Symbol.save"):
        "user-facing Symbol.save (API parity)",
    ("ndarray.utils", "save"):
        "user-facing mx.nd.save (API parity)",
    ("module.module", "Module.save_optimizer_states"):
        "user-facing optimizer-state save (API parity)",
    ("kvstore.kvstore", "KVStore.save_optimizer_states"):
        "user-facing optimizer-state save (API parity)",
    ("gluon.trainer", "Trainer.save_states"):
        "user-facing trainer-state save (API parity)",
    ("onnx.mx2onnx", "export_model"):
        "user-facing ONNX export (API parity)",
    ("profiler", "dump"):
        "user-facing profiler trace dump (API parity)",
    ("telemetry.trace", "dump"):
        "user-facing request-trace dump (tooling output path)",
    ("io.io", "write_token_shard"):
        "dataset-prep shard author — offline single-writer tool path",
}


def register_seam(modkey, qualname, reason):
    """Register an additional torn-file seam at runtime (tests, embedders
    with their own atomic writers)."""
    TORN_SEAMS[(str(modkey), str(qualname))] = str(reason)


# ====================================================================== #
# AST scan model                                                         #
# ====================================================================== #

class _Fn:
    __slots__ = ("modkey", "path", "qualname", "lineno", "end_lineno",
                 "acquires", "calls", "writes", "filesites", "json_reads",
                 "thread_targets", "is_threaded", "src_segment")

    def __init__(self, modkey, path, qualname, lineno, end_lineno):
        self.modkey = modkey
        self.path = path
        self.qualname = qualname
        self.lineno = lineno
        self.end_lineno = end_lineno
        self.acquires = []       # (lockid, line, held tuple of (id, line))
        self.calls = []          # (ref, line, held tuple)
        self.writes = []         # (stateid, line, held frozenset, suppressed)
        self.filesites = []      # (kind, line, suppressed)
        self.json_reads = []     # (line, guarded, suppressed)
        self.thread_targets = [] # ref
        self.is_threaded = False
        self.src_segment = ""


class _FileScan:
    """One file's collected facts (phase 1 of 2; cross-file resolution
    happens in :class:`_Model`)."""

    def __init__(self, path, modkey, source):
        self.path = path
        self.modkey = modkey
        self.relpath = None      # set by _Model
        self.aliases = {}        # local name -> modkey of package module
        self.locks = {}          # lockid -> (kind, line)
        self.globals_mutable = {}  # name -> line
        self.fns = {}            # qualname -> _Fn
        self.thread_targets = [] # ref ("name", n) | ("self", cls, m) | ("mod", a, f)
        self.atomic_intervals = []  # (lo, hi) line ranges exempt via atomic_write
        self.suppress_atomic = set()   # lines with the atomic marker
        self.suppress_torn = set()     # lines with the torn-ok marker
        self._lines = source.split("\n")
        # grammar: the marker terminates the line (reasons go on the
        # comment line above) — keeps doc/message mentions from counting
        for i, ln in enumerate(self._lines, 1):
            stripped = ln.rstrip()
            if stripped.endswith("# concur: atomic"):
                self.suppress_atomic.add(i)
            elif stripped.endswith("# concur: torn-ok"):
                self.suppress_torn.add(i)
        self._source = source

    # ------------------------------------------------------------ helpers --
    def _segment(self, node):
        lo = max(node.lineno - 1, 0)
        hi = node.end_lineno or node.lineno
        return "\n".join(self._lines[lo:hi])

    def _is_lock_ctor(self, node):
        """`threading.Lock()` / `Lock()` / `_threading.RLock()` -> kind."""
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        name = None
        if isinstance(f, ast.Attribute):
            name = f.attr
        elif isinstance(f, ast.Name):
            name = f.id
        return name if name in _LOCK_KINDS else None

    def _is_mutable_ctor(self, node):
        if isinstance(node, (ast.Dict, ast.List, ast.Set)):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            return name in _MUTABLE_CTORS
        return False

    def _resolve_lockref(self, expr, cls):
        """Candidate lock id for a `with` item / `.acquire()` receiver."""
        if isinstance(expr, ast.Name):
            return f"{self.modkey}.{expr.id}"
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                         ast.Name):
            base = expr.value.id
            if base == "self" and cls:
                return f"{self.modkey}.{cls}.{expr.attr}"
            if base in self.aliases:
                return f"{self.aliases[base]}.{expr.attr}"
        return None

    def _call_ref(self, func):
        """Reference for one-level call following / thread targets."""
        if isinstance(func, ast.Name):
            return ("name", func.id)
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            base = func.value.id
            if base == "self":
                return ("self", func.attr)
            if base in self.aliases:
                return ("mod", self.aliases[base], func.attr)
        return None

    # ------------------------------------------------------------- driver --
    def scan(self, tree):
        self._collect_imports(tree)
        self._collect_atomic_intervals(tree)
        for node in tree.body:
            if isinstance(node, ast.Assign):
                self._module_assign(node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                self._module_binding(node.target.id, node.value, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(node, cls=None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(item, cls=node.name)
        return self

    def _collect_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                self._import_from(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    # absolute `import mxnet_tpu.x.y as z`
                    parts = a.name.split(".")
                    if parts[0] == "mxnet_tpu" and len(parts) > 1:
                        local = a.asname or parts[-1]
                        self.aliases[local] = ".".join(parts[1:])

    def _import_from(self, node):
        if node.level:
            parts = self.modkey.split(".") if self.modkey else []
            base = parts[:-node.level] if node.level <= len(parts) else []
            prefix = list(base)
            if node.module:
                prefix += node.module.split(".")
        elif node.module and node.module.split(".")[0] == "mxnet_tpu":
            prefix = node.module.split(".")[1:]
        else:
            return
        for a in node.names:
            local = a.asname or a.name
            self.aliases[local] = ".".join(prefix + [a.name]) if prefix \
                else a.name

    def _collect_atomic_intervals(self, tree):
        """Line intervals of local defs / lambdas passed to an
        ``atomic_write(...)`` call — their file writes are the sanctioned
        writer-callback pattern (mxlint's sync-exemption technique)."""
        defs = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(
                    (node.lineno, node.end_lineno or node.lineno))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "atomic_write":
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    self.atomic_intervals.append(
                        (arg.lineno, arg.end_lineno or arg.lineno))
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    self.atomic_intervals.extend(defs[arg.id])

    def _module_assign(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self._module_binding(tgt.id, node.value, node)

    def _module_binding(self, name, value, node):
        kind = self._is_lock_ctor(value)
        if kind:
            self.locks[f"{self.modkey}.{name}"] = (kind, node.lineno)
        elif self._is_mutable_ctor(value):
            self.globals_mutable[name] = node.lineno

    # ----------------------------------------------------- function walk --
    def _scan_fn(self, node, cls):
        qual = f"{cls}.{node.name}" if cls else node.name
        fn = _Fn(self.modkey, self.path, qual, node.lineno,
                 node.end_lineno or node.lineno)
        fn.src_segment = self._segment(node)
        self.fns[qual] = fn
        self._globals_declared = set()
        self._walk_block(node.body, fn, cls, held=(), guards=frozenset())

    def _walk_block(self, stmts, fn, cls, held, guards):
        for st in stmts:
            self._walk_stmt(st, fn, cls, held, guards)

    def _walk_stmt(self, st, fn, cls, held, guards):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in st.items:
                self._scan_expr(item.context_expr, fn, cls, tuple(new_held),
                                guards)
                lockid = self._resolve_lockref(item.context_expr, cls)
                if lockid is not None:
                    fn.acquires.append((lockid, st.lineno,
                                        tuple(new_held)))
                    new_held.append((lockid, st.lineno))
            self._walk_block(st.body, fn, cls, tuple(new_held), guards)
        elif isinstance(st, ast.Try):
            caught = set()
            for h in st.handlers:
                if h.type is None:
                    caught.add("Exception")
                else:
                    for n in ast.walk(h.type):
                        if isinstance(n, ast.Name):
                            caught.add(n.id)
                        elif isinstance(n, ast.Attribute):
                            caught.add(n.attr)
            self._walk_block(st.body, fn, cls, held,
                             guards | frozenset(caught))
            for h in st.handlers:
                self._walk_block(h.body, fn, cls, held, guards)
            self._walk_block(st.orelse, fn, cls, held, guards)
            self._walk_block(st.finalbody, fn, cls, held, guards)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (writer callback, loop closure): its body runs
            # later, NOT under the current lock set
            self._walk_block(st.body, fn, cls, held=(), guards=frozenset())
        elif isinstance(st, ast.ClassDef):
            for item in st.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_block(item.body, fn, cls, held=(),
                                     guards=frozenset())
        elif isinstance(st, (ast.If, ast.While)):
            self._scan_expr(st.test, fn, cls, held, guards)
            self._walk_block(st.body, fn, cls, held, guards)
            self._walk_block(st.orelse, fn, cls, held, guards)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._scan_expr(st.iter, fn, cls, held, guards)
            self._walk_block(st.body, fn, cls, held, guards)
            self._walk_block(st.orelse, fn, cls, held, guards)
        elif isinstance(st, ast.Global):
            self._globals_declared.update(st.names)
        else:
            self._scan_simple(st, fn, cls, held, guards)

    # ----------------------------------------------------- simple stmts --
    def _scan_simple(self, st, fn, cls, held, guards):
        if isinstance(st, (ast.Assign, ast.AugAssign)):
            targets = st.targets if isinstance(st, ast.Assign) else \
                [st.target]
            for tgt in targets:
                self._write_target(tgt, st, fn, cls, held,
                                   aug=isinstance(st, ast.AugAssign))
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                self._scan_call(node, fn, cls, held, guards)

    def _scan_expr(self, expr, fn, cls, held, guards):
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(node, fn, cls, held, guards)

    def _held_ids(self, held):
        return frozenset(h for h, _ in held)

    def _write_target(self, tgt, st, fn, cls, held, aug):
        line = st.lineno
        suppressed = line in self.suppress_atomic
        # NAME[...] = v  /  NAME[...] += v   on a module-level container
        if isinstance(tgt, ast.Subscript):
            base = tgt.value
            if isinstance(base, ast.Name) and \
                    base.id in self.globals_mutable:
                fn.writes.append((f"{self.modkey}.{base.id}", line,
                                  self._held_ids(held), suppressed))
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls:
                fn.writes.append((f"{self.modkey}.{cls}.{base.attr}",
                                  line, self._held_ids(held), suppressed))
        # NAME += v with a `global NAME` declaration: read-modify-write
        elif isinstance(tgt, ast.Name) and aug and \
                tgt.id in self._globals_declared:
            fn.writes.append((f"{self.modkey}.{tgt.id}", line,
                              self._held_ids(held), suppressed))
        # self.X += v: read-modify-write on shared instance state
        elif isinstance(tgt, ast.Attribute) and aug and \
                isinstance(tgt.value, ast.Name) and \
                tgt.value.id == "self" and cls:
            fn.writes.append((f"{self.modkey}.{cls}.{tgt.attr}", line,
                              self._held_ids(held), suppressed))

    def _scan_call(self, node, fn, cls, held, guards):
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        line = node.lineno

        # ---- thread entry points -------------------------------------
        if fname in ("Thread", "Timer"):
            target = None
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
            if target is None and fname == "Timer" and len(node.args) >= 2:
                target = node.args[1]
            if target is not None:
                ref = self._call_ref(target)
                if ref:
                    self.thread_targets.append(ref)

        # ---- lock acquire as a point event ---------------------------
        if fname == "acquire" and isinstance(f, ast.Attribute):
            lockid = self._resolve_lockref(f.value, cls)
            if lockid is not None:
                fn.acquires.append((lockid, line, tuple(held)))

        # ---- container-mutating method on a shared object ------------
        if fname in _MUTATORS and isinstance(f, ast.Attribute):
            base = f.value
            suppressed = line in self.suppress_atomic
            if isinstance(base, ast.Name) and \
                    base.id in self.globals_mutable:
                fn.writes.append((f"{self.modkey}.{base.id}", line,
                                  self._held_ids(held), suppressed))
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls:
                fn.writes.append((f"{self.modkey}.{cls}.{base.attr}",
                                  line, self._held_ids(held), suppressed))
            # NAME[k].append(...) on a module container
            elif isinstance(base, ast.Subscript) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id in self.globals_mutable:
                fn.writes.append((f"{self.modkey}.{base.value.id}", line,
                                  self._held_ids(held), suppressed))

        # ---- torn-file write sites -----------------------------------
        torn_ok = line in self.suppress_torn
        if fname == "open" and isinstance(f, ast.Name):
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and mode[:1] in ("w", "a", "x"):
                fn.filesites.append(("open-w", line, torn_ok))
        elif fname == "replace" and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "os":
            fn.filesites.append(("os.replace", line, torn_ok))
        elif fname == "dump" and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "json":
            fn.filesites.append(("json.dump", line, torn_ok))
        elif fname == "load" and isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "json":
            guarded = bool(guards & _TORN_GUARDS)
            fn.json_reads.append((line, guarded, torn_ok))

        # ---- direct calls for one-level following --------------------
        ref = self._call_ref(f)
        if ref is not None and fname not in _MUTATORS:
            fn.calls.append((ref, line, tuple(held)))


# ====================================================================== #
# Cross-file model + passes 1-3                                          #
# ====================================================================== #

class _Model:
    """The package-wide scan: lock table, acquisition graph, shared-state
    write census, torn-file sites."""

    def __init__(self, root, files):
        self.root = os.path.abspath(root)
        self.files = {}          # modkey -> _FileScan
        self.locks = {}          # lockid -> (kind, relpath, line)
        self.edges = {}          # a -> {b: (site_a, site_b, via)}
        self.suppressions = {"atomic": 0, "torn": 0}
        self.errors = []         # unparseable files (path, message)
        for path in files:
            self._scan_file(path)
        self._build_lock_table()
        self._mark_threaded()
        self._build_edges()

    # ------------------------------------------------------------ intake --
    def _relpath(self, path):
        rel = os.path.relpath(os.path.abspath(path), self.root)
        return rel.replace(os.sep, "/")

    def _scan_file(self, path):
        rel = self._relpath(path)
        modkey = rel[:-3] if rel.endswith(".py") else rel
        modkey = modkey.replace("/", ".")
        if modkey.endswith(".__init__"):
            modkey = modkey[: -len(".__init__")]
        elif modkey == "__init__":
            modkey = ""
        # normalise scans rooted at the repo (mxlint) vs the package dir:
        # seam-registry keys are package-relative
        if modkey == "mxnet_tpu":
            modkey = ""
        elif modkey.startswith("mxnet_tpu."):
            modkey = modkey[len("mxnet_tpu."):]
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            self.errors.append((rel, str(e)))
            return
        fs = _FileScan(path, modkey, source).scan(tree)
        fs.relpath = rel
        self.files[modkey] = fs
        self.suppressions["atomic"] += len(fs.suppress_atomic)
        self.suppressions["torn"] += len(fs.suppress_torn)

    def _build_lock_table(self):
        for fs in self.files.values():
            for lockid, (kind, line) in fs.locks.items():
                self.locks[lockid] = (kind, fs.relpath, line)
        # instance locks assigned in __init__ (self.X = Lock()) need a
        # second sweep: they live in fn walks, not module bindings
        for fs in self.files.values():
            for qual, fn in fs.fns.items():
                cls = qual.split(".")[0] if "." in qual else None
                if not cls:
                    continue
                seg = fn.src_segment
                for kind in _LOCK_KINDS:
                    needle = f"{kind}("
                    idx = 0
                    while True:
                        idx = seg.find(needle, idx)
                        if idx < 0:
                            break
                        # `self.NAME = threading.Kind(` on the same line
                        lstart = seg.rfind("\n", 0, idx) + 1
                        linetxt = seg[lstart:idx]
                        if "self." in linetxt and "=" in linetxt:
                            attr = linetxt.split("self.", 1)[1]
                            attr = attr.split("=", 1)[0].strip()
                            if attr.isidentifier():
                                lockid = f"{fs.modkey}.{cls}.{attr}"
                                if lockid not in self.locks:
                                    line = fn.lineno + seg.count(
                                        "\n", 0, idx)
                                    self.locks[lockid] = (
                                        kind, fs.relpath, line)
                        idx += len(needle)

    # -------------------------------------------------- thread closure --
    def _resolve_fn(self, fs, ref, caller=None):
        kind = ref[0]
        if kind == "name":
            return fs.fns.get(ref[1])
        if kind == "self":
            if len(ref) == 3:           # ("self", cls, meth) thread target
                return fs.fns.get(f"{ref[1]}.{ref[2]}")
            if caller and "." in caller.qualname:
                cls = caller.qualname.split(".")[0]
                return fs.fns.get(f"{cls}.{ref[1]}")
            # entry ref without class context: match any class's method
            for qual, fn in fs.fns.items():
                if qual.endswith("." + ref[1]):
                    return fn
            return None
        if kind == "mod":
            other = self.files.get(ref[1])
            return other.fns.get(ref[2]) if other else None
        return None

    def _mark_threaded(self):
        worklist = []
        for fs in self.files.values():
            for ref in fs.thread_targets:
                fn = self._resolve_fn(fs, ref)
                if fn is not None:
                    worklist.append(fn)
        seen = set()
        while worklist:
            fn = worklist.pop()
            key = (fn.modkey, fn.qualname)
            if key in seen:
                continue
            seen.add(key)
            fn.is_threaded = True
            fs = self.files.get(fn.modkey)
            if fs is None:
                continue
            for ref, _line, _held in fn.calls:
                callee = self._resolve_fn(fs, ref, caller=fn)
                if callee is not None and \
                        (callee.modkey, callee.qualname) not in seen:
                    worklist.append(callee)

    # ------------------------------------------------------ lock graph --
    def _site(self, fs, line):
        return f"{fs.relpath}:{line}"

    def _add_edge(self, a, b, site_a, site_b, via=""):
        if a == b:
            return
        self.edges.setdefault(a, {})
        if b not in self.edges[a]:
            self.edges[a][b] = (site_a, site_b, via)

    def _build_edges(self):
        for fs in self.files.values():
            for fn in fs.fns.values():
                for lockid, line, held in fn.acquires:
                    if lockid not in self.locks:
                        continue
                    for h, hline in held:
                        if h in self.locks:
                            self._add_edge(h, lockid,
                                           self._site(fs, hline),
                                           self._site(fs, line))
                for ref, line, held in fn.calls:
                    if not held:
                        continue
                    callee = self._resolve_fn(fs, ref, caller=fn)
                    if callee is None:
                        continue
                    cfs = self.files.get(callee.modkey)
                    if cfs is None:
                        continue
                    for lockid, aline, _h in callee.acquires:
                        if lockid not in self.locks:
                            continue
                        for h, hline in held:
                            if h in self.locks:
                                self._add_edge(
                                    h, lockid, self._site(fs, hline),
                                    self._site(cfs, aline),
                                    via=f"via {callee.qualname}() called "
                                        f"at {self._site(fs, line)}")


def _collect_files(root=None, files=None):
    if files:
        root = root or os.path.commonpath(
            [os.path.dirname(os.path.abspath(f)) or "." for f in files])
        return root, sorted(files)
    root = root or _package_root()
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for name in filenames:
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return root, sorted(out)


_scan_cache = {}
_scan_cache_lock = threading.Lock()


def scan(root=None, files=None):
    """Build (and for the default package scan, cache) the cross-file
    concurrency model: lock table, acquisition graph, write census."""
    root, file_list = _collect_files(root, files)
    key = (root, tuple(file_list)) if files is None else None
    if key is not None:
        with _scan_cache_lock:
            model = _scan_cache.get(key)
        if model is not None:
            return model
    model = _Model(root, file_list)
    if key is not None:
        with _scan_cache_lock:
            _scan_cache[key] = model
    return model


# ---------------------------------------------------------------- pass 1 --

def _find_cycles(edges, cap=20):
    """Enumerate simple cycles (deduped by node set), shortest first."""
    cycles, seen = [], set()
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack and len(cycles) < cap:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start and len(path) >= 2:
                    key = frozenset(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(path))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    cycles.sort(key=len)
    return cycles


def check_lock_order(model=None, root=None, files=None):
    """Pass 1: cycles in the static lock-acquisition graph. Each cycle is
    one error Issue naming every acquisition site on the loop."""
    model = model or scan(root=root, files=files)
    issues = []
    for cycle in _find_cycles(model.edges):
        hops = []
        first_site = None
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            site_a, site_b, via = model.edges[a][b]
            if first_site is None:
                first_site = site_a
            hop = (f"{a} (held at {site_a}) -> {b} (acquired at "
                   f"{site_b}{', ' + via if via else ''})")
            hops.append(hop)
        issues.append(Issue(
            "error", "lock-order-cycle", first_site, "",
            "potential deadlock — lock-acquisition cycle: "
            + "; ".join(hops)
            + ". Impose one global order (acquire the locks in a fixed "
              "sequence) or collapse to a single lock."))
    return issues


# ---------------------------------------------------------------- pass 2 --

def check_shared_state(model=None, root=None, files=None):
    """Pass 2: state written from thread-entry-reachable code AND from
    non-thread code with no common lock across all write sites."""
    model = model or scan(root=root, files=files)
    states = {}   # stateid -> [(fs, fn, line, held, suppressed)]
    for fs in model.files.values():
        for fn in fs.fns.values():
            for stateid, line, held, suppressed in fn.writes:
                states.setdefault(stateid, []).append(
                    (fs, fn, line, held, suppressed))
    issues = []
    for stateid in sorted(states):
        sites = [s for s in states[stateid] if not s[4]]
        if not sites:
            continue
        threaded = [s for s in sites if s[1].is_threaded]
        plain = [s for s in sites if not s[1].is_threaded]
        if not threaded or not plain:
            continue
        common = frozenset.intersection(*[s[3] for s in sites])
        if common:
            continue
        t_fs, t_fn, t_line = threaded[0][0], threaded[0][1], threaded[0][2]
        p_fs, p_fn, p_line = plain[0][0], plain[0][1], plain[0][2]
        issues.append(Issue(
            "warning", "unlocked-shared-state",
            f"{t_fs.relpath}:{t_line}", t_fn.qualname,
            f"{stateid!r} is written from thread-entry code here and "
            f"from non-thread code at {p_fs.relpath}:{p_line} "
            f"({p_fn.qualname}) with no lock held in common across the "
            f"write sites — the _atomic_json bug class. Guard every "
            f"write with one shared lock, or mark a provably GIL-atomic "
            f"single-op idiom with `# concur: atomic`."))
    return issues


# ---------------------------------------------------------------- pass 3 --

def check_torn_files(model=None, root=None, files=None):
    """Pass 3: raw write sites off the atomic_write/seam path, seams with
    tmp names missing pid+thread-ident, and unguarded protocol reads."""
    model = model or scan(root=root, files=files)
    issues = []
    for modkey in sorted(model.files):
        fs = model.files[modkey]
        for qual in sorted(fs.fns):
            fn = fs.fns[qual]
            in_seam = (modkey, qual) in TORN_SEAMS
            for kind, line, suppressed in fn.filesites:
                if suppressed or in_seam:
                    continue
                if any(lo <= line <= hi for lo, hi in fs.atomic_intervals):
                    continue
                issues.append(Issue(
                    "warning", "torn-file-write",
                    f"{fs.relpath}:{line}", qual,
                    f"raw {kind} outside checkpoint.atomic_write and the "
                    f"seam registry — a reader can observe a torn "
                    f"record. Route through atomic_write(path, writer), "
                    f"register a seam with its own tmp+fsync+replace "
                    f"protocol, or mark `# concur: torn-ok`."))
            if in_seam and any(k == "os.replace" for k, _l, _s
                               in fn.filesites):
                seg = fn.src_segment
                has_pid = "getpid" in seg
                has_tid = ("get_ident" in seg or "native_id" in seg
                           or "current_thread" in seg)
                if not (has_pid and has_tid):
                    rline = next(l for k, l, _s in fn.filesites
                                 if k == "os.replace")
                    missing = []
                    if not has_pid:
                        missing.append("os.getpid()")
                    if not has_tid:
                        missing.append("threading.get_ident()")
                    issues.append(Issue(
                        "warning", "torn-tmp-name",
                        f"{fs.relpath}:{rline}", qual,
                        f"seam does tmp+os.replace but its tmp name does "
                        f"not embed {' and '.join(missing)} — two "
                        f"threads writing the same path race on one tmp "
                        f"file and the loser's os.replace dies with "
                        f"FileNotFoundError (the PR 16 worker-exit bug)."))
            for line, guarded, suppressed in fn.json_reads:
                if guarded or suppressed:
                    continue
                issues.append(Issue(
                    "warning", "torn-read",
                    f"{fs.relpath}:{line}", qual,
                    "json.load without a torn-record guard visible in "
                    "this function — wrap in try/except ValueError (or "
                    "broader) and skip/retry, or mark `# concur: "
                    "torn-ok` if the input cannot be mid-replace."))
    return issues


# ====================================================================== #
# Pass 4 — runtime lock witness                                          #
# ====================================================================== #

_wit_lock = threading.Lock()      # guards pairs/wrapped bookkeeping
_wit_pairs = {}                   # (a, b) -> {"sites","thread","t"}
_wit_local = threading.local()
_wit_wrapped = []                 # (module, attr, original lock)
_wit_armed = False
_wit_ring = None
_wit_seq = itertools.count(1)
_wit_last_inversion = None


class _Ring:
    """Constant-memory acquisition ring (flight-recorder style): slots
    are whole tuples stored with one GIL-atomic list assignment, so a
    reader never observes a torn record."""

    def __init__(self, capacity):
        self.capacity = max(int(capacity), 8)
        self._slots = [None] * self.capacity

    def record(self, rec):
        seq = next(_wit_seq)                     # GIL-atomic claim
        self._slots[(seq - 1) % self.capacity] = (seq,) + rec

    def tail(self, n=None):
        live = [s for s in self._slots if s is not None]
        live.sort()
        return live[-n:] if n else live


def _ring():
    global _wit_ring
    if _wit_ring is None:
        _wit_ring = _Ring(int(os.environ.get(ENV_RING, "512")))
    return _wit_ring


def _held_stack():
    st = getattr(_wit_local, "stack", None)
    if st is None:
        st = _wit_local.stack = []
    return st


def _call_site(skip=2):
    f = sys._getframe(skip)
    here = os.path.abspath(__file__)
    while f is not None and os.path.abspath(f.f_code.co_filename) == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    path = f.f_code.co_filename
    try:
        rel = os.path.relpath(path, _package_root())
        if not rel.startswith(".."):
            path = rel.replace(os.sep, "/")
        else:
            path = os.path.basename(path)
    except ValueError:
        path = os.path.basename(path)
    return f"{path}:{f.f_lineno}"


class _WitnessLock:
    """Transparent wrapper recording acquisition order per thread. RLock
    re-entry generates no pairs; unknown attributes (Condition's wait /
    notify, RLock internals) delegate to the wrapped object."""

    def __init__(self, lock, name):
        self._lock = lock
        self.name = name

    def acquire(self, *args, **kwargs):
        ok = self._lock.acquire(*args, **kwargs)
        if ok:
            self._note_acquire()
        return ok

    def release(self):
        self._note_release()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._lock, name)

    def _note_acquire(self):
        stack = _held_stack()
        reentrant = any(n == self.name for n, _ in stack)
        site = _call_site(3)
        if not reentrant:
            for held_name, held_site in stack:
                key = (held_name, self.name)
                if key not in _wit_pairs:
                    with _wit_lock:
                        if key not in _wit_pairs:
                            _wit_pairs[key] = {
                                "sites": (held_site, site),
                                "thread": threading.current_thread().name,
                                "t": time.time(),
                            }
        stack.append((self.name, site))
        _ring().record((time.time(), threading.get_ident(),
                        threading.current_thread().name, self.name,
                        "acquire", site))

    def _note_release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == self.name:
                del stack[i]
                break
        _ring().record((time.time(), threading.get_ident(),
                        threading.current_thread().name, self.name,
                        "release", _call_site(3)))


def wrap(lock, name):
    """Wrap one lock explicitly (instance locks the module sweep cannot
    reach, or test fixtures)."""
    if isinstance(lock, _WitnessLock):
        return lock
    return _WitnessLock(lock, str(name))


def trace_locks(register_atexit=False):
    """Arm the witness: wrap every module-level Lock/RLock/Condition the
    static scan found (modules imported lazily and best-effort). Returns
    the number of locks wrapped; no-op (0) when ``MXNET_TPU_CONCUR=0``
    or already armed. ``register_atexit=True`` additionally cross-checks
    at interpreter exit, printing (not raising) any inversion."""
    global _wit_armed
    if not enabled() or _wit_armed:
        return 0
    import importlib

    model = scan()
    wrapped = 0
    with _wit_lock:
        for lockid, (kind, _rel, _line) in sorted(model.locks.items()):
            if lockid.startswith("analysis.concur."):
                continue       # never wrap the witness's own bookkeeping
            parts = lockid.split(".")
            # module-level locks only: "<modkey>.<ATTR>" where modkey is
            # a scanned file; class-qualified ids are instance locks
            attr = parts[-1]
            modkey = ".".join(parts[:-1])
            if modkey not in model.files:
                continue
            try:
                mod = importlib.import_module(
                    f"mxnet_tpu.{modkey}" if modkey else "mxnet_tpu")
            except Exception:
                continue
            obj = getattr(mod, attr, None)
            if obj is None or isinstance(obj, _WitnessLock) or \
                    not hasattr(obj, "acquire"):
                continue
            setattr(mod, attr, _WitnessLock(obj, lockid))
            _wit_wrapped.append((mod, attr, obj))
            wrapped += 1
        _wit_armed = True
    if register_atexit:
        import atexit

        atexit.register(_atexit_check)
    return wrapped


def untrace_locks():
    """Disarm: restore every wrapped module attribute. Witnessed pairs
    and the ring survive for inspection until :func:`reset_witness`."""
    global _wit_armed
    with _wit_lock:
        for mod, attr, original in _wit_wrapped:
            current = getattr(mod, attr, None)
            if isinstance(current, _WitnessLock):
                setattr(mod, attr, original)
        restored = len(_wit_wrapped)
        del _wit_wrapped[:]
        _wit_armed = False
    return restored


def reset_witness():
    """Drop witnessed pairs, the ring, and the last-inversion record
    (call between chaos phases while threads are quiescent)."""
    global _wit_ring, _wit_last_inversion
    with _wit_lock:
        _wit_pairs.clear()
        _wit_ring = None
        _wit_last_inversion = None


def _inversions(static_edges=None):
    found = []
    with _wit_lock:
        pairs = dict(_wit_pairs)
    for (a, b), rec in sorted(pairs.items()):
        rev = pairs.get((b, a))
        if rev is not None and a < b:
            found.append((
                (a, b), rec, (b, a), rev,
                "witnessed in both orders at runtime"))
    if static_edges:
        for (a, b), rec in sorted(pairs.items()):
            fwd = static_edges.get(a, {})
            if b in fwd:
                continue                      # witnessed order matches
            back = static_edges.get(b, {})
            if a in back:
                sa, sb, _via = back[a]
                found.append((
                    (a, b), rec, (b, a),
                    {"sites": (sa, sb), "thread": "<static>", "t": 0},
                    "inverts the statically established order"))
    return found


def check_witness(raise_=True, static=True):
    """Cross-check witnessed acquisition order against itself and (by
    default) the static graph. Returns the inversion list; with
    ``raise_`` a non-empty list raises :class:`LockOrderError` naming
    both acquisition sites and the witnessing thread."""
    global _wit_last_inversion
    static_edges = scan().edges if static else None
    found = _inversions(static_edges)
    if found:
        (a, b), rec, (_b2, _a2), other, why = found[0]
        msg = (f"lock-order inversion: {a} then {b} witnessed at "
               f"{rec['sites'][0]} -> {rec['sites'][1]} "
               f"[thread {rec['thread']}], but the opposite order "
               f"{_b2} -> {_a2} holds at {other['sites'][0]} -> "
               f"{other['sites'][1]} ({why})")
        _wit_last_inversion = msg
        if raise_:
            raise LockOrderError(msg)
    return found


def _atexit_check():
    try:
        found = check_witness(raise_=False)
        if found:
            sys.stderr.write(
                f"[concur] WARNING: {_wit_last_inversion}\n")
    except Exception:
        pass


def witness_state():
    """Witness status for diagnose: armed flag, wrapped-lock count,
    witnessed ordered pairs, ring occupancy, last inversion."""
    with _wit_lock:
        return {
            "armed": _wit_armed,
            "wrapped": len(_wit_wrapped),
            "pairs": len(_wit_pairs),
            "ring": len([s for s in (_wit_ring._slots if _wit_ring
                                     else ()) if s is not None]),
            "last_inversion": _wit_last_inversion,
        }


def witness_tail(n=32):
    """Last-N lock acquisitions/releases across all threads (crash
    bundles embed this next to the flight tail)."""
    out = []
    if _wit_ring is None:
        return out
    for seq, t, ident, tname, lockname, op, site in _wit_ring.tail(n):
        out.append({"seq": seq, "t": t, "thread_id": ident,
                    "thread": tname, "lock": lockname, "op": op,
                    "site": site})
    return out


# ====================================================================== #
# Orchestrator                                                           #
# ====================================================================== #

def run_static(files=None, root=None, passes=("locks", "shared", "torn")):
    """Passes 1-3 over an explicit file set (mxlint's entry point; no
    env gate so the lint rules stay deterministic)."""
    model = scan(root=root, files=files)
    issues = []
    if "locks" in passes:
        issues += check_lock_order(model)
    if "shared" in passes:
        issues += check_shared_state(model)
    if "torn" in passes:
        issues += check_torn_files(model)
    return issues


def run(root=None, files=None, passes=None, witness=False,
        raise_on_error=True):
    """Run the analyzer; returns the combined Issue list.

    ``analysis.concur(...)`` resolves here (the module is callable).
    Default: passes 1-3 over the installed package; ``witness=True``
    additionally cross-checks the armed runtime witness. Honours
    ``MXNET_TPU_CONCUR=0`` (returns ``[]``)."""
    if not enabled():
        return []
    issues = run_static(files=files, root=root,
                        passes=passes or ("locks", "shared", "torn"))
    if witness:
        for (a, b), rec, rev_key, other, why in check_witness(raise_=False):
            issues.append(Issue(
                "error", "lock-order-witnessed", rec["sites"][1], "",
                f"witnessed inversion: {a} -> {b} at {rec['sites'][0]} "
                f"-> {rec['sites'][1]} [thread {rec['thread']}] {why}; "
                f"opposite order at {other['sites'][0]} -> "
                f"{other['sites'][1]}"))
    if raise_on_error:
        return _raise_if_errors(issues)
    return issues


class _CallableModule(types.ModuleType):
    """``analysis.concur(...)`` — the module is its own entry point.
    ``ConcurError`` materialises on first access (verify.py stays off
    the import path; standalone loads fall back to RuntimeError)."""

    def __call__(self, *args, **kwargs):
        return run(*args, **kwargs)

    def __getattr__(self, name):
        if name == "ConcurError":
            cls = _realise_error_class()
            self.ConcurError = cls
            return cls
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")


_self = sys.modules.get(__name__)
if _self is not None:
    _self.__class__ = _CallableModule
