"""Distributed-correctness static analyzer: the parallel-layer verifier.

PR 3 grew the single-process analysis layer (graph verifier, sync-hazard
sanitizer, mxlint); this module extends it to the *parallel* layer — the
class of silent distributed bugs that otherwise surface as XLA error
spelunking, a wedged gang, or a 100x-slower run:

* **Pass 1 — sharding verifier** (:func:`check_sharding`): propagate
  per-parameter PartitionSpecs against the :class:`DeviceMesh` axes.
  Undefined/duplicated axis names (with difflib did-you-mean, mirroring the
  OpSchema hints), spec rank vs array rank, dims not divisible by the axis
  size, and large parameters silently fully replicated while the mesh has a
  model axis.
* **Pass 2 — collective-order deadlock detector**
  (:func:`collective_schedule` / :class:`ScheduleRecorder` /
  :func:`cross_check_schedule`): extract each rank's static collective
  schedule (from compiled HLO, or recorded live at the kvstore collectives),
  fingerprint it, and cross-check the fingerprints through the kvstore
  barrier — two ranks issuing collectives in different orders raise a
  structured :class:`CollectiveOrderError` *at the barrier*, pre-empting the
  wedge that ``PeerLostError`` can only report after the deadline.
* **Pass 3 — donation-safety checker** (:func:`mark_donated` /
  :func:`check_live`): donated buffers (``ShardedTrainer``'s in-place
  parameter update) are poisoned in a registry; any later use of a stale
  alias — eager dispatch, the bulking recorder, a ``CachedOp`` call, or
  forcing a poisoned :class:`~mxnet_tpu.bulk.LazyRef` — raises a
  :class:`DonatedBufferError` naming the parameter and the donating step,
  instead of jax's anonymous "Array has been deleted".
* **Pass 4 — recompile-churn detector** (:func:`cache_event` /
  :func:`check_churn`): the dispatch/compile caches (``ops/registry.py``
  jit cache, ``bulk.py`` fused-segment cache, ``cached_op.py`` signature
  cache) report every lookup here; per-call-site distinct-key counts expose
  unstable keys — per-step shape/dtype drift that recompiles every step.

Findings are reported through the same structured
:class:`~mxnet_tpu.analysis.verify.Issue` list the graph verifier uses;
errors raise :class:`DistCheckError` (a ``GraphVerifyError`` subclass, so
``.issues`` carries the full list).

``ShardedTrainer`` auto-runs :func:`check_trainer` before compiling its
step executable; ``MXNET_TPU_DISTCHECK=0`` opts out of the auto-run, the
donation poisoning, and the cache tracking in one knob. The module itself
is callable — ``mxnet_tpu.analysis.distcheck(...)`` is :func:`run`.
"""
from __future__ import annotations

import os
import re
import sys
import threading
import types
import weakref
from collections import deque

from ..base import MXNetError, did_you_mean

__all__ = ["enabled", "run", "DistCheckError", "DistCheckWarning",
           "DonatedBufferError", "CollectiveOrderError",
           "check_sharding", "check_trainer",
           "collective_schedule", "schedule_from_hlo",
           "schedule_fingerprint", "compare_schedules", "ScheduleRecorder",
           "cross_check_schedule",
           "mark_donated", "check_live", "donated_count", "clear_donated",
           "cache_event", "cache_stats", "check_churn", "reset_cache_stats",
           "track_caches"]

ENV = "MXNET_TPU_DISTCHECK"

# canonical mesh-axis vocabulary lives in parallel/mesh.py (AXIS_ORDER);
# duplicated by tools/mxlint.py's partition-spec-literal rule.

_LARGE_PARAM_ELEMS = int(os.environ.get("MXNET_TPU_DISTCHECK_LARGE",
                                        str(1 << 20)))


def enabled() -> bool:
    """The ``MXNET_TPU_DISTCHECK`` gate (on unless explicitly disabled):
    controls the ShardedTrainer auto-run, donation poisoning, and
    compile-cache tracking."""
    return os.environ.get(ENV, "1").lower() not in ("0", "false", "off")


class DistCheckWarning(UserWarning):
    """A warning-severity distcheck finding (e.g. a large parameter left
    fully replicated on a mesh with a model axis)."""


def _issue(severity, code, node, op, message):
    # verify.py pulls in the symbol/registry layers; load it on first
    # finding, not at import (this module must stay import-light — the
    # dispatch hot paths read module attributes here)
    from .verify import Issue

    return Issue(severity, code, node, op, message)


def _realise_error_class():
    """``DistCheckError`` subclasses ``GraphVerifyError`` (same structured
    ``.issues`` payload), but verify.py pulls in the registry layers — so
    the class is created on first access (module ``__getattr__`` below),
    keeping this module import-light for the dispatch hot paths."""
    from .verify import GraphVerifyError

    class DistCheckError(GraphVerifyError):
        """Distributed-correctness verification failed; ``.issues``
        carries the structured finding list (errors + warnings)."""

    DistCheckError.__module__ = __name__
    return DistCheckError


def _raise_if_errors(issues, warn=True):
    import warnings

    if warn:
        for i in issues:
            if not i.is_error:
                warnings.warn(str(i), DistCheckWarning, stacklevel=3)
    if any(i.is_error for i in issues):
        raise sys.modules[__name__].DistCheckError(issues)
    return issues


# ====================================================================== #
# Pass 1 — sharding verifier                                             #
# ====================================================================== #

def check_sharding(rules, shapes, mesh, batch_shape=None,
                   large_param_elems=None):
    """Propagate PartitionSpecs against the mesh; returns the Issue list.

    Parameters
    ----------
    rules : {param_name: PartitionSpec tuple} — axis names / None entries.
    shapes : {param_name: shape tuple} for every parameter in `rules`.
    mesh : DeviceMesh whose ``axis_names``/``axis_sizes`` the specs must
        resolve against.
    batch_shape : optional data-batch shape checked for dp divisibility.
    large_param_elems : threshold (elements) above which a fully
        replicated parameter on a mesh with a >1 model axis is flagged
        (default 2**20; ``MXNET_TPU_DISTCHECK_LARGE`` overrides).
    """
    if large_param_elems is None:
        large_param_elems = _LARGE_PARAM_ELEMS
    axes = tuple(mesh.axis_names)
    issues = []
    model_axes = [a for a in axes
                  if a != "dp" and mesh.axis_sizes.get(a, 1) > 1]
    for name, spec in rules.items():
        spec = tuple(spec or ())
        shape = shapes.get(name)
        if shapes and shape is None:
            issues.append(_issue(
                "warning", "unknown-param", name, None,
                "sharding rule names no known parameter"
                + did_you_mean(name, shapes) + " — the rule is dead"))
        seen = set()
        for i, ax in enumerate(spec):
            if ax is None:
                continue
            for ax_name in (ax if isinstance(ax, (tuple, list)) else (ax,)):
                if ax_name is None:
                    continue
                if ax_name not in axes:
                    issues.append(_issue(
                        "error", "undefined-axis", name, None,
                        f"PartitionSpec {spec} on {mesh!r}: "
                        + mesh.axis_error(ax_name)
                        + " — jax would silently replicate this "
                        "dimension instead of sharding it"))
                    continue
                if ax_name in seen:
                    issues.append(_issue(
                        "error", "duplicate-axis", name, None,
                        f"PartitionSpec {spec} uses mesh axis "
                        f"{ax_name!r} for more than one dimension; an "
                        "axis may shard at most one dimension of an "
                        "array"))
                    continue
                seen.add(ax_name)
                if shape is not None and i < len(shape):
                    size = mesh.axis_sizes.get(ax_name, 1)
                    if size > 1 and int(shape[i]) % size != 0:
                        issues.append(_issue(
                            "error", "indivisible-dim", name, None,
                            f"dimension {i} (size {shape[i]}) of shape "
                            f"{tuple(shape)} is sharded over axis "
                            f"{ax_name!r} of size {size} but is not "
                            f"divisible by it — XLA would pad every "
                            "shard; fix the rule or the mesh"))
        if shape is not None and len(spec) > len(shape):
            issues.append(_issue(
                "error", "spec-rank", name, None,
                f"PartitionSpec {spec} has {len(spec)} entries for an "
                f"array of rank {len(shape)} (shape {tuple(shape)}); a "
                "spec may not be longer than the array rank"))
        if shape is not None and model_axes and not any(
                s is not None for s in spec):
            elems = 1
            for d in shape:
                elems *= int(d)
            if elems >= large_param_elems:
                issues.append(_issue(
                    "warning", "replicated-large-param", name, None,
                    f"parameter of shape {tuple(shape)} ({elems:,} "
                    "elements) is fully replicated although the mesh "
                    f"has model axes {model_axes} — every device holds "
                    "a full copy; consider a sharding rule"))
    if batch_shape is not None and "dp" in axes:
        dp = mesh.axis_sizes.get("dp", 1)
        if dp > 1 and (not batch_shape or int(batch_shape[0]) % dp != 0):
            issues.append(_issue(
                "error", "batch-indivisible", "<data batch>", None,
                f"batch shape {tuple(batch_shape)} is sharded over the "
                f"'dp' axis of size {dp} but its leading dimension is "
                "not divisible by it — feed a batch divisible by the "
                "dp size (or shrink the dp axis)"))
    return issues


def check_trainer(trainer, x_raw=None, y_raw=None, raise_on_error=True):
    """The ShardedTrainer auto-run: sharding-verify its rules (params +
    ZeRO/optimizer state layouts) against its mesh, plus data-batch dp
    divisibility when a batch is given. Called before the step executable
    compiles; ``MXNET_TPU_DISTCHECK=0`` opts out."""
    mesh = trainer._mesh
    rules = {}
    shapes = {}
    handles = list(zip(trainer._param_names, trainer._train_handles)) \
        + list(zip(trainer._aux_names, trainer._aux_handles))
    for name, h in handles:
        rules[name] = tuple(trainer._rules.get(name, ()))
        shapes[name] = tuple(h._data.shape)
    for name, spec in trainer._rules.items():
        rules.setdefault(name, tuple(spec or ()))  # dead-rule typo check
    # ZeRO state layouts are derived (divisible by construction) but user
    # rule overrides flow into them — validate the param rules trimmed to
    # each state slot's rank, mirroring _state_spec_for
    for name, per in zip(trainer._param_names, trainer._opt_raws):
        base = tuple(trainer._rules.get(name, ()))
        for j, s in enumerate(per):
            key = f"{name} (optimizer state {j})"
            rules[key] = base[:len(s.shape)]
            shapes[key] = tuple(s.shape)
    batch_shape = tuple(x_raw.shape) if x_raw is not None else None
    issues = check_sharding(rules, shapes, mesh, batch_shape=batch_shape)
    if raise_on_error:
        return _raise_if_errors(issues)
    return issues


# ====================================================================== #
# Pass 2 — collective-order deadlock detector                            #
# ====================================================================== #

_HLO_COLLECTIVES = re.compile(
    r"\b(all-reduce(?:-start)?|all-gather(?:-start)?|"
    r"reduce-scatter|collective-permute(?:-start)?|all-to-all)\b")
_HLO_SHAPE = re.compile(r"=\s*(\([^)]*\)|[a-z0-9\[\],]+)\s")
_HLO_GROUPS = re.compile(r"replica_groups=(\{[^}]*\}|\[[^\]]*\][^,)]*)")


def schedule_from_hlo(hlo_text):
    """Extract the static collective schedule from compiled HLO text: an
    ordered list of ``(kind, result_type, replica_groups)`` entries, one
    per collective op, in program order."""
    sched = []
    for line in hlo_text.splitlines():
        m = _HLO_COLLECTIVES.search(line)
        if m is None or "=" not in line:
            continue
        kind = m.group(1).replace("-start", "")
        shape = _HLO_SHAPE.search(line)
        groups = _HLO_GROUPS.search(line)
        sched.append((kind,
                      shape.group(1) if shape else "?",
                      groups.group(1) if groups else "?"))
    return sched


def collective_schedule(fn, *avals, in_shardings=None, out_shardings=None,
                        donate_argnums=()):
    """Compile ``fn`` abstractly and return its static collective schedule
    (:func:`schedule_from_hlo` of the optimized HLO). ``fn`` may already be
    jitted; otherwise it is wrapped with the given shardings. No device
    buffers are touched — inputs are ``jax.ShapeDtypeStruct``s."""
    import jax

    if hasattr(fn, "lower"):
        jf = fn
    else:
        kw = {}
        if in_shardings is not None:
            kw["in_shardings"] = in_shardings
        if out_shardings is not None:
            kw["out_shardings"] = out_shardings
        if donate_argnums:
            kw["donate_argnums"] = donate_argnums
        jf = jax.jit(fn, **kw)
    compiled = jf.lower(*avals).compile()
    return schedule_from_hlo(compiled.as_text())


def schedule_fingerprint(schedule):
    """Stable hex fingerprint of a collective schedule (count-prefixed
    sha1) — small enough to allgather and compare across ranks."""
    import hashlib

    h = hashlib.sha1()
    for entry in schedule:
        h.update(repr(entry).encode())
    return f"{len(schedule)}:{h.hexdigest()[:16]}"


def compare_schedules(schedules):
    """Cross-rank schedule comparison: ``schedules`` is ``{rank: [entry,
    ...]}``. Returns Issues — empty when every rank's schedule matches,
    otherwise one ``collective-order`` error naming the first divergent
    position and what each rank issues there (the deadlock shape: each
    rank blocks in a different collective)."""
    ranks = sorted(schedules)
    if len(ranks) < 2:
        return []
    ref_rank = ranks[0]
    ref = list(schedules[ref_rank])
    issues = []
    for rank in ranks[1:]:
        sched = list(schedules[rank])
        if sched == ref:
            continue
        pos = next((i for i, (a, b) in enumerate(zip(ref, sched))
                    if a != b), min(len(ref), len(sched)))
        a = ref[pos] if pos < len(ref) else "<end of schedule>"
        b = sched[pos] if pos < len(sched) else "<end of schedule>"
        issues.append(_issue(
            "error", "collective-order", f"collective #{pos}", None,
            f"rank {ref_rank} and rank {rank} issue different "
            f"collective schedules: at position {pos} rank {ref_rank} "
            f"issues {a!r} but rank {rank} issues {b!r} "
            f"({len(ref)} vs {len(sched)} collectives total) — "
            "mismatched schedules deadlock the gang; make every rank "
            "run the same collective sequence (same keys, same order)"))
    return issues


class ScheduleRecorder:
    """Constant-memory live recorder of one rank's collective schedule.

    The dist kvstore notes every collective here (``push``/``allreduce``/
    ``barrier`` with the key involved); a running sha1 plus a bounded tail
    of recent entries gives a fingerprint that every rank can compare at
    the next barrier without unbounded growth."""

    __slots__ = ("count", "_hash", "tail", "_lock")

    def __init__(self, tail=64):
        import hashlib

        self.count = 0
        self._hash = hashlib.sha1()
        self.tail = deque(maxlen=tail)
        self._lock = threading.Lock()

    def note(self, op, detail=""):
        with self._lock:
            self.count += 1
            entry = (op, str(detail))
            self._hash.update(repr(entry).encode())
            self.tail.append(entry)

    def fingerprint(self):
        with self._lock:
            return f"{self.count}:{self._hash.hexdigest()[:16]}"

    def digest_words(self):
        """The fingerprint as 3 int64 words (count + 16 hash hex chars)
        — the allgather payload for the cross-rank check."""
        with self._lock:
            d = int(self._hash.hexdigest()[:16], 16)
        return [self.count, d >> 32, d & 0xFFFFFFFF]


class CollectiveOrderError(MXNetError):
    """Ranks recorded divergent collective schedules — raised at the
    kvstore barrier, before the divergence can wedge a real collective.
    Carries ``rank``, ``fingerprints`` (per-rank), and ``tail`` (this
    rank's recent schedule entries) for the post-mortem."""

    def __init__(self, rank, fingerprints, tail):
        self.rank = rank
        self.fingerprints = dict(fingerprints)
        self.tail = list(tail)
        lines = ", ".join(f"rank {r}: {fp}"
                          for r, fp in sorted(self.fingerprints.items()))
        recent = "; ".join(f"{op}({d})" for op, d in self.tail[-8:])
        super().__init__(
            f"collective-order divergence detected at the kvstore barrier "
            f"(rank {rank}): schedule fingerprints differ across ranks "
            f"[{lines}] — a deadlock was imminent. This rank's recent "
            f"collectives: [{recent}]. Make every rank push/pull the same "
            "keys in the same order.")


def cross_check_schedule(recorder, kv=None, allgather=None):
    """Cross-rank fingerprint check: allgather every rank's schedule
    digest and raise :class:`CollectiveOrderError` on divergence.

    ``allgather`` is ``fn(list[int]) -> per-rank rows`` (dependency
    injection for tests); by default ``jax.experimental.multihost_utils.
    process_allgather`` is used. With one worker this is a no-op. The
    allgather itself is symmetric (fixed shape on every rank), so it
    cannot deadlock even when the recorded schedules already diverged."""
    import jax

    if allgather is None:
        if jax.process_count() < 2:
            return
        from jax.experimental.multihost_utils import process_allgather

        import numpy as _np

        def allgather(words):
            return process_allgather(_np.asarray(words, _np.int64))

    rank = kv.rank if kv is not None else jax.process_index()
    rows = allgather(recorder.digest_words())
    fps = {}
    rows = [list(map(int, r)) for r in rows]
    for r, row in enumerate(rows):
        fps[r] = f"{row[0]}:{(row[1] << 32 | row[2]):016x}"
    if len(set(fps.values())) > 1:
        raise CollectiveOrderError(rank, fps, recorder.tail)


# ====================================================================== #
# Pass 3 — donation-safety checker                                       #
# ====================================================================== #

# id(raw jax.Array) -> (param_name, origin, step, weakref keeping the id
# valid). Non-empty DONATED is the one-word gate the dispatch paths check;
# weakref callbacks prune entries as the stale buffers are collected, so
# the registry tracks only donated buffers that still have live aliases.
DONATED = {}
_donated_lock = threading.Lock()


class DonatedBufferError(MXNetError):
    """A buffer donated to a compiled step was used afterwards. Carries
    ``name`` (the parameter), ``origin`` (who donated), ``step``, and
    ``where`` (the use site class)."""

    def __init__(self, name, origin, step, where):
        self.name = name
        self.origin = origin
        self.step = step
        self.where = where
        super().__init__(
            f"use-after-donate: buffer of {name!r} was donated to "
            f"{origin}" + (f" at step {step}" if step is not None else "")
            + f" and its memory no longer exists, but {where} is reading "
            "it. Re-read the parameter through its handle "
            "(e.g. param.data()) after each step instead of holding a "
            "stale alias, or construct the trainer with donate=False.")


def mark_donated(buf, name, origin, step=None):
    """Poison one donated buffer. ``buf`` may be a raw jax array, an
    NDArray handle (its buffer is poisoned; a pending LazyRef is poisoned
    in place so forcing it raises), or a LazyRef."""
    from ..bulk import LazyRef

    ref = getattr(buf, "_buf", buf)  # NDArray -> its buffer slot
    record = (name, origin, step)
    if type(ref) is LazyRef:
        ref.donated = record
        if ref._value is None:
            return
        ref = ref._value
    key = id(ref)

    def _expire(_wr, _key=key):
        with _donated_lock:
            DONATED.pop(_key, None)

    try:
        wr = weakref.ref(ref, _expire)
    except TypeError:
        wr = None
    with _donated_lock:
        DONATED[key] = (name, origin, step, wr)
        if len(DONATED) > 65536:  # belt-and-braces against callback loss
            for k in list(DONATED)[:32768]:
                DONATED.pop(k, None)


def donated_count():
    return len(DONATED)


def clear_donated():
    with _donated_lock:
        DONATED.clear()


def check_live(raws, where):
    """Raise :class:`DonatedBufferError` if any of ``raws`` is a poisoned
    (donated) buffer. Call sites gate on the truthiness of
    :data:`DONATED` so the disabled cost is one dict check. A hit is
    confirmed via ``is_deleted()`` where available, so id reuse can never
    produce a false positive."""
    for raw in raws:
        rec = DONATED.get(id(raw))
        if rec is None:
            continue
        name, origin, step, wr = rec
        if wr is not None and wr() is not raw:
            with _donated_lock:  # stale id (buffer was collected, id reused)
                DONATED.pop(id(raw), None)
            continue
        deleted = getattr(raw, "is_deleted", None)
        if deleted is not None and not deleted():
            continue  # donation did not actually consume it (backend quirk)
        raise DonatedBufferError(name, origin, step, where)


# ====================================================================== #
# Pass 4 — recompile-churn detector                                      #
# ====================================================================== #

# (kind, site) -> [hits, misses, key-set, last_key, drift_samples]
_CACHE_SITES = {}
_KEY_CAP = 256

CACHE_TRACK = enabled()


def track_caches(on=True):
    """Toggle compile-cache tracking at runtime (set from the env gate at
    import). The dispatch hot paths read :data:`CACHE_TRACK` directly."""
    global CACHE_TRACK
    CACHE_TRACK = bool(on)


def cache_event(kind, site, key, hit):
    """One dispatch/compile cache lookup. ``kind`` is the cache family
    (``dispatch``/``bulk``/``cachedop``), ``site`` the call site (op name,
    CachedOp identity), ``key`` the cache key. Hot-path cheap: a hit is a
    dict lookup + an increment."""
    rec = _CACHE_SITES.get((kind, site))
    if rec is None:
        rec = _CACHE_SITES[(kind, site)] = [0, 0, set(), None, []]
    if hit:
        rec[0] += 1
        return
    rec[1] += 1
    try:
        if len(rec[2]) < _KEY_CAP:
            rec[2].add(key)
        prev = rec[3]
        rec[3] = key
        if prev is not None and prev != key and len(rec[4]) < 8:
            rec[4].append((prev, key))
    except TypeError:
        pass  # unhashable key — counted, not remembered
    from .. import profiler as _profiler

    if _profiler._RECORDING:
        _profiler.record_cache(kind, rec[0], rec[1])


def cache_stats():
    """Per-site compile-cache statistics: ``{(kind, site): {hits, misses,
    distinct_keys}}`` — the measurement seam for the unified compile
    service (ROADMAP item 5) and the ``tools/diagnose.py`` report."""
    out = {}
    for (kind, site), rec in sorted(_CACHE_SITES.items()):
        out[(kind, site)] = {"hits": rec[0], "misses": rec[1],
                             "distinct_keys": len(rec[2])}
    return out


def reset_cache_stats():
    _CACHE_SITES.clear()


def _describe_drift(prev, new, path=()):
    """First structural difference between two cache keys, as a
    human-readable component path (shape/dtype drift usually)."""
    if type(prev) is tuple and type(new) is tuple and len(prev) == len(new):
        for i, (a, b) in enumerate(zip(prev, new)):
            if a != b:
                return _describe_drift(a, b, path + (i,))
        return "?"
    loc = "".join(f"[{i}]" for i in path) or "key"
    return f"{loc}: {prev!r} -> {new!r}"


def check_churn(min_misses=4, max_hit_ratio=0.5):
    """Flag call sites whose compile-cache keys churn: at least
    ``min_misses`` distinct compilations with a hit ratio at or below
    ``max_hit_ratio`` (per-step shape/dtype drift compiles a fresh
    executable every call). Returns warning Issues naming the site and
    the drifting key component."""
    issues = []
    for (kind, site), rec in sorted(_CACHE_SITES.items()):
        hits, misses, keys, _last, drift = rec
        calls = hits + misses
        if misses < min_misses or calls == 0:
            continue
        if hits / calls > max_hit_ratio:
            continue
        detail = ""
        if drift:
            detail = ("; drifting key component: "
                      + _describe_drift(*drift[-1]))
        issues.append(_issue(
            "warning", "cache-churn", site, kind,
            f"{misses} compile-cache misses in {calls} calls "
            f"({len(keys)} distinct keys seen) — the cache key is "
            f"unstable, so this site recompiles instead of reusing an "
            f"executable{detail}. Pad/bucket the inputs to stable "
            "shapes, or hoist the varying value into a traced argument"))
    return issues


# ====================================================================== #
# Orchestrator                                                           #
# ====================================================================== #

def run(trainer=None, *, rules=None, shapes=None, mesh=None,
        batch_shape=None, schedules=None, churn=True, raise_on_error=True):
    """Run every applicable pass; returns the combined Issue list.

    ``analysis.distcheck(...)`` resolves here (the module is callable).
    Pass a ``trainer`` (ShardedTrainer) for its full sharding surface, or
    raw ``rules``/``shapes``/``mesh`` (+ optional ``batch_shape``). Pass
    ``schedules`` ({rank: schedule}) for the cross-rank comparison and
    leave ``churn`` on to sweep the compile-cache statistics."""
    issues = []
    if trainer is not None:
        issues += check_trainer(trainer, raise_on_error=False)
    if rules is not None and mesh is not None:
        issues += check_sharding(rules, shapes or {}, mesh,
                                 batch_shape=batch_shape)
    if schedules:
        issues += compare_schedules(schedules)
    if churn:
        issues += check_churn()
    if raise_on_error:
        return _raise_if_errors(issues)
    return issues


class _CallableModule(types.ModuleType):
    """``analysis.distcheck(...)`` — the module is its own entry point.
    ``DistCheckError`` materialises on first access (verify.py stays off
    the import path of the dispatch hot paths)."""

    def __call__(self, *args, **kwargs):
        return run(*args, **kwargs)

    def __getattr__(self, name):
        if name == "DistCheckError":
            cls = _realise_error_class()
            self.DistCheckError = cls
            return cls
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")


sys.modules[__name__].__class__ = _CallableModule
