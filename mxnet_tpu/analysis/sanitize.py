"""Sync-hazard sanitizer: runtime-assisted checking of the engine contract.

Two hazard classes the reference's threaded engine made loud but XLA's async
dispatch makes silent:

1. **Implicit host syncs** — ``asnumpy`` / ``asscalar`` / ``__bool__`` /
   ``wait_to_read`` / forcing a lazy buffer. Each one stalls the dispatch
   pipeline for a device round-trip; one inside a training loop body is the
   #1 silent perf killer. Worse, a sync while a :class:`~mxnet_tpu.bulk.
   BulkSegment` is open *splits the segment*: the ops recorded so far
   compile as a fragment, losing the fusion the bulking engine exists to
   provide. The sanitizer records every sync with its user call site and
   flags the segment-splitting ones as hazards.

2. **Output-aval contract violations** — the bulking recorder trusts each
   op's predicted ``output_avals`` (cached ``jax.eval_shape``) to wire
   downstream ops without executing. An op whose runtime output diverges
   from its abstract prediction (nondeterministic emitter, stale cache,
   buggy custom op) corrupts every segment it appears in. Under the
   sanitizer, both the eager dispatch path and the fused segment runner
   cross-check actual outputs against the prediction and report violations
   with op name and call site.

Enabled via ``MXNET_TPU_SANITIZE=1`` (read at import) or
:func:`enable` / the :func:`sanitize` context manager. When disabled the
only cost at each sync point is one module-attribute truthiness check.

Events are queryable (:func:`events`, :func:`hazards`) and hazards are also
emitted as :class:`SyncHazardWarning` via ``warnings.warn`` so they surface
in test runs and ``-W error`` CI configurations.
"""
from __future__ import annotations

import contextlib
import os
import threading
import warnings
from collections import deque

__all__ = ["SyncHazardWarning", "SyncEvent", "enable", "disable", "sanitize",
           "record_sync", "check_contract", "events", "hazards", "reset",
           "ACTIVE"]

ACTIVE = os.environ.get("MXNET_TPU_SANITIZE", "0").lower() \
    not in ("", "0", "false", "off")

_MAX_EVENTS = 4096

_events = deque(maxlen=_MAX_EVENTS)
_lock = threading.Lock()
_tls = threading.local()

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class SyncHazardWarning(UserWarning):
    """A host sync split a live bulk segment, or an op violated its
    output-aval contract."""


class SyncEvent:
    """One recorded sync point / contract check."""

    __slots__ = ("kind", "site", "pending", "hazard", "message")

    def __init__(self, kind, site, pending, hazard, message):
        self.kind = kind        # asnumpy/asscalar/bool/wait_to_read/...
        self.site = site        # "file:lineno in func" of the user frame
        self.pending = pending  # ops pending in the thread's bulk segment
        self.hazard = hazard
        self.message = message

    def __repr__(self):
        flag = "HAZARD " if self.hazard else ""
        return f"<SyncEvent {flag}{self.kind} at {self.site}: {self.message}>"


# ----------------------------------------------------------------- knobs ---

def enable():
    global ACTIVE
    ACTIVE = True


def disable():
    global ACTIVE
    ACTIVE = False


@contextlib.contextmanager
def sanitize():
    """Scoped enablement: ``with sanitize(): ...`` (tests, profiling runs)."""
    global ACTIVE
    prev = ACTIVE
    ACTIVE = True
    try:
        yield
    finally:
        ACTIVE = prev


def reset():
    with _lock:
        _events.clear()


def events():
    with _lock:
        return list(_events)


def hazards():
    return [e for e in events() if e.hazard]


# ------------------------------------------------------------- recording ---

def _callsite():
    """First stack frame outside the mxnet_tpu package — where the user
    triggered the sync."""
    import sys

    f = sys._getframe(1)
    while f is not None:
        fname = f.f_code.co_filename
        internal = fname.startswith(_PKG_DIR) \
            or fname.endswith("contextlib.py")
        if not internal or os.sep + "tests" in fname:
            return f"{fname}:{f.f_lineno} in {f.f_code.co_name}"
        f = f.f_back
    return "<library internal>"


def _record(kind):
    from .. import bulk

    pending = bulk.pending_ops()
    hazard = pending > 0
    site = _callsite()
    if hazard:
        message = (f"host sync ({kind}) split a live bulk segment of "
                   f"{pending} recorded op"
                   f"{'s' if pending != 1 else ''} — the segment "
                   "compiles as a fragment, losing fusion")
    else:
        message = f"host sync ({kind})"
    ev = SyncEvent(kind, site, pending, hazard, message)
    with _lock:
        _events.append(ev)
    if hazard:
        warnings.warn(f"{message} [at {site}]", SyncHazardWarning,
                      stacklevel=3)


@contextlib.contextmanager
def synced(kind):
    """Record one sync event and suppress nested recording for the span of
    the enclosed host-sync operation (``asscalar`` -> ``asnumpy`` ->
    ``LazyRef.force`` records once, under the outermost — most precise —
    kind). Callers check :data:`ACTIVE` first."""
    if getattr(_tls, "in_sync", False):
        yield
        return
    _tls.in_sync = True
    try:
        _record(kind)
        yield
    finally:
        _tls.in_sync = False


def record_sync(kind):
    """Point-record for sync events with no enclosed span (``wait_all``,
    ``LazyRef.force`` reached through a raw ``_data`` read). No-op when a
    :func:`synced` scope already recorded the outer operation."""
    if getattr(_tls, "in_sync", False):
        return
    _record(kind)


# ------------------------------------------------------ contract checking --

def check_contract(op, raws, kwargs, kw_key, raw_out):
    """Cross-check an eager op's actual outputs against the registry's
    predicted ``output_avals`` (the FInferShape/FInferType analogue the
    bulking recorder trusts blindly). Called from ``ndarray._invoke`` when
    :data:`ACTIVE`."""
    if op.eager or (kw_key is None and kwargs):
        return  # no abstract prediction exists for this call
    try:
        in_sig = tuple((tuple(r.shape), r.dtype) for r in raws)
        avals, single = op.output_avals(in_sig, kwargs, kw_key)
    except Exception:
        return  # inference itself failed; the op already ran fine
    outs = raw_out if isinstance(raw_out, (tuple, list)) else (raw_out,)
    _compare(op.name, [(tuple(av.shape), av.dtype) for av in avals], outs)


def check_segment(plan, refs, live, outs):
    """Fused-segment variant: compare the executed segment's outputs with
    the LazyRef avals the recorder promised downstream consumers. Called
    from ``BulkSegment.run`` when :data:`ACTIVE`."""
    ops_hint = [p[0] for p in plan]
    for pos, flat_idx in enumerate(live):
        if pos >= len(outs):
            break
        ref = refs[flat_idx]
        _compare(f"bulk segment output {flat_idx}",
                 [(tuple(ref.shape), ref.dtype)], (outs[pos],),
                 plan_hint=ops_hint)


def _compare(what, predicted, outs, plan_hint=None):
    import numpy as _np

    problems = []
    if len(predicted) != len(outs):
        problems.append(f"predicted {len(predicted)} outputs, "
                        f"got {len(outs)}")
    for i, ((pshape, pdtype), out) in enumerate(zip(predicted, outs)):
        ashape = tuple(out.shape)
        if pshape != ashape:
            problems.append(f"output {i}: predicted shape {pshape}, "
                            f"actual {ashape}")
        elif pdtype is not None and _np.dtype(pdtype) != _np.dtype(out.dtype):
            problems.append(f"output {i}: predicted dtype {pdtype}, "
                            f"actual {out.dtype}")
    if not problems:
        return
    site = _callsite()
    message = (f"output-aval contract violation in {what}: "
               + "; ".join(problems))
    if plan_hint:
        message += f" (segment ops: {plan_hint})"
    ev = SyncEvent("contract", site, 0, True, message)
    with _lock:
        _events.append(ev)
    warnings.warn(f"{message} [at {site}]", SyncHazardWarning, stacklevel=4)
