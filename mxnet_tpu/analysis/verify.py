"""Graph verifier: static checking passes over the Symbol ``_Node`` DAG.

Parity target: the reference's correctness guarantees come from NNVM graph
passes — ``InferShape`` / ``InferType`` run *before* execution
(`src/executor/infer_graph_attr_pass.cc`), op attribute validation via
``dmlc::Parameter::Init``, and the graph indexing layer rejecting malformed
node references. Our reproduction re-grew the execution half; this module is
the verification half: a set of topo-walk passes that run ahead of
``bind``/``eval`` and turn "TypeError deep inside a jit trace" into a
node-level diagnostic.

Passes (all collected into one :class:`Issue` list; none executes device
code — shape/dtype work happens abstractly via ``jax.eval_shape``):

* **cycle**           — back-edge detection over ``_Node.inputs`` (possible
                        via hand-mutated graphs or crafted/corrupt JSON).
* **unknown-op**      — node references an op missing from the registry.
* **bad-kwarg**       — per-node hyper-parameters validated against the op's
                        reflected :class:`~mxnet_tpu.ops.schema.OpSchema`.
* **dangling-input**  — an input edge referencing an output index its
                        producer does not have.
* **duplicate-name**  — two distinct variable nodes sharing one name (feed
                        dicts are keyed by name: ambiguous binding); op-node
                        name collisions are reported as warnings.
* **shape-mismatch**  — full shape/dtype inference walk; a node whose
                        abstract evaluation fails is reported with its input
                        shapes, and declared ``__shape__``/``__dtype__``
                        attrs are cross-checked against caller hints.
* **output-arity**    — predicted output count (``jax.eval_shape`` on the op)
                        vs the node's declared ``num_outputs``.
* **dead-output**     — outputs of multi-output nodes that are neither
                        consumed nor graph heads (warning).
* **unused-hint**     — shape/type hints naming no graph input (warning —
                        usually a typo'd feed key).

``Symbol.verify()`` is the public entry; ``simple_bind`` runs the verifier
automatically unless ``MXNET_TPU_VERIFY=0``.
"""
from __future__ import annotations

import os

from ..base import MXNetError, canonical_dtype
from ..ops import registry as _registry
from ..ops.schema import OpParamError

__all__ = ["Issue", "GraphVerifyError", "verify_graph", "verify_enabled",
           "raise_if_errors", "node_failure_message"]


class Issue:
    """One verifier finding, attached to a graph node."""

    __slots__ = ("severity", "code", "node", "op", "message")

    def __init__(self, severity, code, node, op, message):
        self.severity = severity  # "error" | "warning"
        self.code = code
        self.node = node          # node name ("" for graph-level findings)
        self.op = op              # registry op name, or None for variables
        self.message = message

    @property
    def is_error(self):
        return self.severity == "error"

    def __str__(self):
        where = f"node {self.node!r}" if self.node else "graph"
        if self.op:
            where += f" (op {self.op})"
        return f"[{self.severity}:{self.code}] {where}: {self.message}"

    def __repr__(self):
        return f"<Issue {self}>"


class GraphVerifyError(MXNetError):
    """Raised by ``Symbol.verify`` when error-severity issues exist; carries
    the full issue list (warnings included) as ``.issues``."""

    def __init__(self, issues):
        self.issues = list(issues)
        errors = [i for i in self.issues if i.is_error]
        lines = "\n  ".join(str(i) for i in errors)
        super().__init__(
            f"graph verification failed ({len(errors)} error"
            f"{'s' if len(errors) != 1 else ''}):\n  {lines}")


def verify_enabled() -> bool:
    """The ``MXNET_TPU_VERIFY`` gate for the automatic simple_bind run
    (on unless explicitly disabled)."""
    return os.environ.get("MXNET_TPU_VERIFY", "1").lower() \
        not in ("0", "false", "off")


def raise_if_errors(issues):
    if any(i.is_error for i in issues):
        raise GraphVerifyError(issues)
    return issues


def _failure_text(in_shapes, exc):
    shapes = ", ".join(str(tuple(s)) if s is not None else "?"
                       for s in in_shapes)
    return (f"abstract evaluation failed for input shapes [{shapes}]: "
            f"{exc}")


def node_failure_message(node, in_shapes, exc):
    """A node-level diagnostic for an abstract-evaluation failure — shared
    with ``Symbol.infer_shape``'s error path so inference errors always name
    the offending node and op."""
    return f"node {node.name!r} (op {node.op}): " \
        + _failure_text(in_shapes, exc)


# ---------------------------------------------------------------- passes ---

def _walk(entries):
    """Iterative DFS over the node DAG. Returns (postorder, cycle) where
    `cycle` is a list of node names forming a back edge path (empty when the
    graph is acyclic). Unlike ``symbol._topo`` this detects cycles instead
    of silently truncating them."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {}
    order = []
    cycle = []
    for root, _ in entries:
        if color.get(id(root), WHITE) is not WHITE:
            continue
        stack = [(root, iter([c for c, _ in root.inputs]))]
        color[id(root)] = GRAY
        path = [root]
        while stack:
            node, it = stack[-1]
            advanced = False
            for child in it:
                c = color.get(id(child), WHITE)
                if c == GRAY and not cycle:
                    # back edge: report the enclosing path once
                    names = [n.name for n in path]
                    try:
                        start = next(i for i, n in enumerate(path)
                                     if n is child)
                    except StopIteration:
                        start = 0
                    cycle = names[start:] + [child.name]
                    continue
                if c == WHITE:
                    color[id(child)] = GRAY
                    stack.append((child, iter([cc for cc, _
                                               in child.inputs])))
                    path.append(child)
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                path.pop()
                color[id(node)] = BLACK
                order.append(node)
    return order, cycle


def _op_kwargs(node):
    from ..attribute import is_dunder

    return {k: v for k, v in node.attrs.items() if not is_dunder(k)}


def _check_structure(order, entries, issues):
    """Registry lookup, kwargs validation, input-edge sanity, name
    collisions."""
    var_names = {}
    op_names = {}
    head_nodes = {id(n) for n, _ in entries}
    for node in order:
        if node.is_var:
            prev = var_names.get(node.name)
            if prev is not None and prev is not node:
                issues.append(Issue(
                    "error", "duplicate-name", node.name, None,
                    "two distinct variable nodes share this name; feeds "
                    "and gradients are keyed by name, so binding is "
                    "ambiguous"))
            var_names[node.name] = node
            continue
        prev = op_names.get(node.name)
        if prev is not None and prev is not node:
            issues.append(Issue(
                "warning", "duplicate-name", node.name, node.op,
                "another op node uses the same name; saved JSON and "
                "attr_dict entries will collide"))
        op_names[node.name] = node
        try:
            op = _registry.get(node.op)
        except KeyError as exc:
            issues.append(Issue("error", "unknown-op", node.name, node.op,
                                str(exc)))
            continue
        try:
            op.schema.validate(_op_kwargs(node))
        except OpParamError as exc:
            issues.append(Issue("error", "bad-kwarg", node.name, node.op,
                                str(exc)))
        schema = op.schema
        if not schema.variadic and len(node.inputs) > len(schema.inputs):
            issues.append(Issue(
                "error", "dangling-input", node.name, node.op,
                f"{len(node.inputs)} inputs wired to an op declaring at "
                f"most {len(schema.inputs)} ({schema.inputs})"))
        # required inputs may also be satisfied as static attrs (scalar
        # creation ops: sym.arange passes `start` as a keyword)
        min_req = 0 if schema.variadic else sum(
            1 for in_name in schema.inputs[:_min_required(op)]
            if in_name not in node.attrs)
        if len(node.inputs) < min_req:
            issues.append(Issue(
                "error", "dangling-input", node.name, node.op,
                f"only {len(node.inputs)} inputs wired; op requires at "
                f"least {min_req} of {schema.inputs}"))
        for child, oi in node.inputs:
            if oi >= child.num_outputs or oi < 0:
                issues.append(Issue(
                    "error", "dangling-input", node.name, node.op,
                    f"input edge references output {oi} of node "
                    f"{child.name!r}, which has only "
                    f"{child.num_outputs} output"
                    f"{'s' if child.num_outputs != 1 else ''}"))


def _min_required(op):
    """Number of leading array inputs with no default (signature-derived)."""
    import inspect

    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return 0
    n = 0
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.VAR_POSITIONAL,
                      inspect.Parameter.VAR_KEYWORD):
            break
        if p.default is inspect.Parameter.empty \
                and p.kind is not inspect.Parameter.KEYWORD_ONLY:
            n += 1
        else:
            break
    return n


def _check_dead_outputs(order, entries, issues):
    consumed = set()
    for node in order:
        for child, oi in node.inputs:
            consumed.add((id(child), oi))
    heads = {(id(n), i) for n, i in entries}
    for node in order:
        if node.is_var or node.num_outputs <= 1:
            continue
        try:
            op = _registry.get(node.op)
        except KeyError:
            continue
        if not callable(op.num_outputs):
            # fixed multi-output ops (BatchNorm & co) carry auxiliary
            # outputs that are unconsumed by design; only hyper-parameter
            # driven counts (SliceChannel num_outputs=3) are user intent
            continue
        dead = [i for i in range(node.num_outputs)
                if (id(node), i) not in consumed
                and (id(node), i) not in heads]
        if dead and len(dead) < node.num_outputs:
            issues.append(Issue(
                "warning", "dead-output", node.name, node.op,
                f"output{'s' if len(dead) > 1 else ''} "
                f"{dead} of {node.num_outputs} are never consumed "
                "(dead in the lowered graph; XLA prunes them, but the "
                "symbol may be over-computing)"))


def _check_shapes(order, entries, shape_hints, dtype_hints, issues):
    """Abstract shape/dtype walk, tolerant of unknown inputs: every node
    whose inputs are all known is evaluated; failures become node-level
    issues instead of aborting the pass."""
    import jax

    from ..symbol.symbol import _eval_shape_node, _param_shape_rules

    vals = {}

    def _known(shape):
        # MXNet convention: a 0 entry means "unknown dim" (deferred init)
        return shape is not None and all(int(d) > 0 for d in shape)

    def _conflict(a, b):
        return len(a) != len(b) or any(
            int(x) > 0 and int(y) > 0 and int(x) != int(y)
            for x, y in zip(a, b))

    for node in order:
        if node.is_var:
            declared = node.attrs.get("__shape__")
            hinted = shape_hints.get(node.name)
            if declared is not None and hinted is not None \
                    and _conflict(tuple(declared), tuple(hinted)):
                issues.append(Issue(
                    "error", "shape-mismatch", node.name, None,
                    f"declared __shape__ {tuple(declared)} conflicts with "
                    f"bind-time shape {tuple(hinted)}"))
            shape = hinted if _known(hinted) else \
                (declared if _known(declared) else None)
            dtype = dtype_hints.get(node.name,
                                    node.attrs.get("__dtype__", "float32"))
            if shape is not None:
                try:
                    vals[id(node), 0] = jax.ShapeDtypeStruct(
                        tuple(shape), canonical_dtype(dtype))
                except Exception as exc:  # bad dtype/shape attr
                    issues.append(Issue(
                        "error", "shape-mismatch", node.name, None,
                        f"invalid shape/dtype declaration "
                        f"({shape!r}, {dtype!r}): {exc}"))
            continue
        if any(i.is_error and i.node == node.name for i in issues):
            continue  # structural/kwarg error already reported for it
        in_structs = []
        data_struct = None
        for child, oi in node.inputs:
            st = vals.get((id(child), oi))
            if st is not None and data_struct is None:
                data_struct = st
            in_structs.append((child, oi, st))
        try:
            rules = _param_shape_rules(node, data_struct)
        except Exception:
            rules = {}
        resolved = []
        for child, oi, st in in_structs:
            if st is None and child.is_var and child.name in rules:
                try:
                    rshape, rdtype = rules[child.name]
                    st = jax.ShapeDtypeStruct(
                        rshape,
                        canonical_dtype(dtype_hints.get(
                            child.name,
                            child.attrs.get("__dtype__",
                                            rdtype or "float32"))))
                    vals[id(child), 0] = st
                except Exception:
                    st = None
            resolved.append(st)
        if any(st is None for st in resolved):
            continue  # inputs unknown — nothing to check abstractly
        try:
            outs = _eval_shape_node(node, resolved)
        except Exception as exc:  # noqa: BLE001 — converted to a diagnostic
            issues.append(Issue(
                "error", "shape-mismatch", node.name, node.op,
                _failure_text([st.shape for st in resolved], exc)))
            continue
        if len(outs) != node.num_outputs:
            issues.append(Issue(
                "error", "output-arity", node.name, node.op,
                f"op predicts {len(outs)} output"
                f"{'s' if len(outs) != 1 else ''} for these "
                f"hyper-parameters but the node declares "
                f"{node.num_outputs}"))
        for i, st in enumerate(outs):
            vals[id(node), i] = st


def _check_hints(order, shape_hints, dtype_hints, issues):
    input_names = {n.name for n in order if n.is_var}
    for src, hints in (("shape", shape_hints), ("type", dtype_hints)):
        for name in hints:
            if name not in input_names:
                issues.append(Issue(
                    "warning", "unused-hint", name, None,
                    f"{src} hint matches no graph input (inputs: "
                    f"{sorted(input_names)})"))


# ----------------------------------------------------------------- entry ---

def verify_graph(symbol, shape_hints=None, type_dict=None):
    """Run every verifier pass over ``symbol``; returns the Issue list
    (errors and warnings, in pass order). Raises nothing itself — callers
    decide severity handling via :func:`raise_if_errors`."""
    shape_hints = {k: tuple(v) for k, v in (shape_hints or {}).items()}
    dtype_hints = {k: canonical_dtype(v)
                   for k, v in (type_dict or {}).items()}
    issues = []
    entries = symbol._entries
    order, cycle = _walk(entries)
    if cycle:
        issues.append(Issue(
            "error", "cycle", cycle[0], None,
            "graph contains a cycle: " + " -> ".join(repr(n)
                                                     for n in cycle)))
        return issues  # no topological order: downstream passes undefined
    _check_structure(order, entries, issues)
    _check_dead_outputs(order, entries, issues)
    _check_hints(order, shape_hints, dtype_hints, issues)
    # inference consistency only when structure held up enough to try
    if not any(i.code in ("unknown-op",) for i in issues):
        _check_shapes(order, entries, shape_hints, dtype_hints, issues)
    return issues
