"""Imperative engine bulking: fuse eager op segments into one XLA executable.

Parity target: the reference's engine bulking
(`MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN`, `src/imperative/imperative_utils.h:396`
and `Engine::StartBulk/BulkFlush`): consecutive imperative ops are merged into
a single engine job so non-hybridized Gluon training is not dispatch-bound.

TPU-native redesign: "merge N ops into one engine job" becomes "trace N ops
into ONE fused `jax.jit` executable". Each op call that passes the gate in
``ndarray._invoke`` is *recorded* into the thread's open :class:`BulkSegment`
instead of being executed: the caller receives NDArrays whose buffer is a
:class:`LazyRef` placeholder carrying the statically inferred shape/dtype
(via a cached ``jax.eval_shape``). The segment is compiled and executed as a
single executable — cached per (op-sequence, static-kwargs, wiring) plan, with
jit's own signature cache keying shapes/dtypes — when any sync point is hit:

  * a concrete buffer read (``asnumpy``, ``wait_to_read``, control flow on
    values, any raw access through the ``NDArray._data`` property),
  * ``engine.wait_all`` / changing the bulk size / leaving ``engine.bulk``,
  * ``autograd.backward``/``grad`` and recording-state flips,
  * an in-place mutation (``_rebind``) — ordering + tape identity,
  * the segment reaching ``engine.bulk_size()`` nodes (the BulkFlush analogue).

Under ``autograd.record()`` a flushed segment becomes ONE tape node whose
pullback is ``jax.vjp`` of the fused function, recomputing the forward inside
the backward executable — the same rematerialising backward CachedOp uses
(`cached_op.cc:990`; MXNET_BACKWARD_DO_MIRROR is the right default on TPU).

Deferred-error semantics match the engine contract: an op that fails inside a
segment raises at the flush (sync) point, not at the recording call site.

Segments are thread-local. A LazyRef forced from a *different* thread than the
recording one executes the segment directly; the producing thread's next flush
then finds every ref already materialised (assignment is idempotent).
"""
from __future__ import annotations

import threading
import weakref

from . import autograd
from . import compile as _compile
from . import profiler as _profiler
from .analysis import distcheck as _distcheck
from .analysis import sanitize as _sanitize

__all__ = ["LazyRef", "BulkSegment", "record", "flush", "active",
           "pending_ops", "live_segments"]

_tls = threading.local()

# plan -> jitted fused forward; (plan, taped_idx) -> jitted fused vjp.
# jax.jit's own signature cache keys shapes/dtypes below these.
_FUSED_CACHE = {}
_VJP_CACHE = {}

# every not-yet-successfully-executed segment, across threads — the
# watchdog's crash bundles report this as the "live bulk-segment state"
# (a wedged flush shows exactly which fused op sequence was in flight)
_LIVE = weakref.WeakSet()
_live_lock = threading.Lock()


def live_segments():
    """Snapshot of pending/failed segments as plain dicts (crash bundles,
    diagnose tooling). Successful runs remove themselves."""
    with _live_lock:
        segs = list(_LIVE)
    return [{"n_ops": len(s.plan), "ops": [p[0] for p in s.plan],
             "recording": s.recording,
             "error": repr(s.error) if s.error is not None else None}
            for s in segs if s.plan]

_Tracer = None  # lazily bound jax.core.Tracer (keep jax import off cold path)


class LazyRef:
    """Placeholder buffer for one output of a pending bulk segment.

    Shape/dtype are known statically (eval_shape), so metadata queries on a
    lazy NDArray never force execution; only value reads do."""

    __slots__ = ("segment", "flat_idx", "shape", "dtype", "taped", "_value",
                 "donated")

    def __init__(self, segment, flat_idx, shape, dtype, taped):
        self.segment = segment
        self.flat_idx = flat_idx
        self.shape = shape
        self.dtype = dtype
        self.taped = taped
        self._value = None
        self.donated = None  # (name, origin, step) once poisoned

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    def force(self):
        """Materialise: flush the owning segment, return the concrete array."""
        if self.donated is not None:
            # poisoned by distcheck: this buffer was handed to a donating
            # compiled step — reading it is use-after-donate
            name, origin, step = self.donated
            raise _distcheck.DonatedBufferError(
                name, origin, step, "a lazy buffer read")
        if self._value is None:
            if _sanitize.ACTIVE:
                # an implicit value read is splitting the live segment
                _sanitize.record_sync("lazy-force")
            seg = self.segment
            if getattr(_tls, "seg", None) is seg:
                _tls.seg = None
            seg.run()
        return self._value


class BulkSegment:
    """An open sequence of recorded op calls awaiting fused execution."""

    __slots__ = ("recording", "steps", "plan", "ext_raws", "ext_handles",
                 "ext_index", "refs", "handles", "error", "__weakref__")

    def __init__(self, recording):
        self.recording = recording  # autograd state the segment was opened in
        self.steps = []        # (bound_fn, slots, single) per recorded op
        self.plan = []         # hashable (op, kw_key, slots, n_out) per op
        self.ext_raws = []     # concrete jax.Array inputs from outside
        self.ext_handles = []  # their NDArray handles (tape entries / replay)
        self.ext_index = {}    # id(handle) -> ext position (dedup)
        self.refs = []         # flat LazyRef list across all steps
        self.handles = []      # weakrefs to the wrapped output NDArrays
        self.error = None
        with _live_lock:
            _LIVE.add(self)

    def _retire(self):
        with _live_lock:
            _LIVE.discard(self)

    # ----------------------------------------------------------- execute ---
    def run(self):
        """Execute the segment as one fused jitted call and fill the refs.

        Only outputs whose NDArray handle is still alive become executable
        outputs — dropped intermediates stay internal to the XLA program, so
        the compiler fuses straight through them (this is where the bulking
        win comes from; returning every intermediate would force XLA to
        materialise each one). A fully dead segment is skipped outright —
        the engine-level analogue of XLA dead-code elimination.

        Idempotent; a failure is stored and re-raised on later forces (the
        deferred-exception-at-sync-point contract)."""
        if self.error is not None:
            raise self.error
        if not self.plan:
            self._retire()
            return
        live = [i for i, wh in enumerate(self.handles)
                if wh() is not None]
        if not live:
            self._retire()
            return
        from . import faults as _faults
        from . import watchdog as _watchdog

        prof = _profiler._REC_IMPERATIVE
        t0 = _profiler._now_us() if prof else None
        live_t = tuple(live)
        plan_key = (tuple(self.plan), live_t)
        fused = _FUSED_CACHE.get(plan_key)
        if _distcheck.CACHE_TRACK:
            # recompile-churn seam: distinct plans per flush site feed the
            # distcheck cache-stats (tools/diagnose.py "compile cache")
            _distcheck.cache_event("bulk", "BulkSegment", plan_key,
                                   fused is not None)
        if fused is None:
            # compiled through the unified service (mxnet_tpu.compile):
            # the plan (op names + frozen kwargs + wiring) is the
            # process-stable token, so identical segments hit the
            # persistent cache across runs
            fused = _FUSED_CACHE[plan_key] = _compile.jit(
                _build_fused(self.steps, live_t), site="bulk",
                token=("bulk", plan_key))

        def _execute():
            # 'engine.flush' injection point: an injected failure behaves
            # exactly like an op failing inside the fused segment — it
            # surfaces HERE, at the sync point, and stays sticky on the
            # segment (the deferred-exception contract under test)
            _faults.point("engine.flush")
            return fused(*self.ext_raws)

        try:
            # deadline-bounded when an 'engine.flush' watchdog deadline is
            # armed — a wedged flush raises StallError at the sync point
            # (sticky, like any other deferred engine error)
            outs = _watchdog.sync("engine.flush", _execute,
                                  label=f"bulk[{len(self.plan)}]")
        except Exception as exc:
            self.error = exc
            raise
        if _sanitize.ACTIVE:
            # each executed output must match the aval its LazyRef promised
            _sanitize.check_segment(self.plan, self.refs, live, outs)
        for i, val in zip(live, outs):
            self.refs[i]._value = val
        self._retire()  # executed: no longer "live" for crash bundles
        if self.recording:
            taped_idx = tuple(i for i in live if self.refs[i].taped)
            if taped_idx:
                self._record_tape(plan_key, taped_idx)
        if prof:
            _profiler.record_bulk_segment(t0, _profiler._now_us() - t0,
                                          [k[0] for k in plan_key[0]])

    def _record_tape(self, plan_key, taped_idx):
        """One tape node for the whole segment (parity: CachedOp recording a
        single node for its call). The pullback is jax.vjp of the fused
        function over the taped outputs, jitted and cached per plan — the
        forward is rematerialised inside the backward executable."""
        entries = autograd.make_entries(self.ext_handles)
        tape_fn = _build_fused(self.steps, taped_idx)
        vkey = (plan_key, taped_idx)
        vjp_exec = _VJP_CACHE.get(vkey)
        if vjp_exec is None:
            import jax

            def _vjp_run(ext, cots, _fn=tape_fn):
                _, pull = jax.vjp(_fn, *ext)
                return pull(tuple(cots))

            vjp_exec = _VJP_CACHE[vkey] = _compile.jit(
                _vjp_run, site="bulk", token=("bulk-vjp", vkey))
        ext_t = tuple(self.ext_raws)

        def vjp_fn(cots, _exec=vjp_exec, _ext=ext_t):
            cots = cots if isinstance(cots, tuple) else (cots,)
            return _exec(_ext, cots)

        node = autograd.TapeNode(
            "BulkSegment[%d]" % len(self.plan), vjp_fn, entries,
            len(taped_idx),
            [self.refs[i].shape for i in taped_idx],
            [self.refs[i]._value.dtype for i in taped_idx], fwd_fn=tape_fn)
        for pos, i in enumerate(taped_idx):
            h = self.handles[i]()
            if h is not None:
                h._tape_node = node
                h._tape_index = pos


def _build_fused(steps, out_idx):
    """Pure fn(*ext) -> tuple of the flat outputs selected by `out_idx`.
    The python loop runs only while jax traces; the cached executable is
    one XLA program, and unselected intermediates never materialise."""
    steps = list(steps)

    def fused(*ext):
        flat = []
        for fn, slots, single in steps:
            args = [ext[i] if k == 0 else flat[i] for k, i in slots]
            out = fn(*args)
            if single:
                flat.append(out)
            else:
                flat.extend(out)
        return tuple(flat[i] for i in out_idx)

    return fused


def _wrap_lazy(wrap, ref):
    """Construct an output array handle around a LazyRef without the
    NDArray.__init__ device-put path."""
    nd = object.__new__(wrap)
    nd._buf = ref
    nd._grad = None
    nd._grad_req = "null"
    nd._tape_node = None
    nd._tape_index = 0
    nd._fresh_grad = False
    return nd


# ------------------------------------------------------------- module API --

def active() -> bool:
    return getattr(_tls, "seg", None) is not None


def pending_ops() -> int:
    """Number of ops recorded in the current (unflushed) segment."""
    seg = getattr(_tls, "seg", None)
    return len(seg.plan) if seg is not None else 0


def flush() -> None:
    """Execute and close the thread's open segment (the BulkFlush analogue).
    No-op when nothing is pending."""
    seg = getattr(_tls, "seg", None)
    if seg is None:
        return
    _tls.seg = None
    seg.run()


def record(op, kwargs, kw_key, nd_inputs, wrap, size):
    """Try to append one imperative op call to the current segment.

    Returns the wrapped lazy output(s), or None when the call is not
    bulkable — dynamic-output-shape (eager) ops, unhashable kwargs, tracer
    inputs (already inside a CachedOp trace), or outputs of another
    thread's pending segment — in which case the caller falls through to
    the per-op dispatch path (whose buffer reads flush as needed).
    """
    if op.eager or (kw_key is None and kwargs):
        return None
    global _Tracer
    if _Tracer is None:
        from jax.core import Tracer as _T

        _Tracer = _T
    seg = getattr(_tls, "seg", None)
    recording = autograd.is_recording()
    if seg is not None and seg.recording != recording:
        # belt-and-braces: set_recording flushes on flips, but a segment
        # opened under a different autograd state must never mix
        flush()
        seg = None
    n_ext = len(seg.ext_raws) if seg is not None else 0
    slots, in_sig = [], []
    staged = None  # (handle, raw) inputs to commit; lazy — most calls hit
    any_tape = False
    for x in nd_inputs:
        buf = getattr(x, "_buf", None)
        if type(buf) is LazyRef and buf._value is None:
            if buf.segment is not seg:
                return None
            slots.append((1, buf.flat_idx))
            in_sig.append((buf.shape, buf.dtype))
            any_tape = any_tape or buf.taped
            continue
        if type(buf) is LazyRef:
            raw = buf._value
        elif buf is None:  # sparse storage: dense view
            raw = x._data
        else:
            raw = buf
        if isinstance(raw, _Tracer):
            return None
        if _distcheck.DONATED:
            # use-after-donate caught at RECORD time — before the stale
            # buffer is wired into a fused segment
            _distcheck.check_live((raw,), f"op {op.name!r} (bulked)")
        pos = seg.ext_index.get(id(x)) if seg is not None else None
        if pos is None:
            if staged is None:
                staged = {}
            hit = staged.get(id(x))
            if hit is None:
                pos = n_ext + len(staged)
                staged[id(x)] = (pos, x, raw)
            else:
                pos = hit[0]
        slots.append((0, pos))
        s = raw.shape  # jax arrays expose shape as a tuple already
        in_sig.append((s if type(s) is tuple else tuple(s), raw.dtype))
        if recording and (x._tape_node is not None
                          or x._grad_req != "null"):
            any_tape = True
    try:
        avals, single = op.output_avals(tuple(in_sig), kwargs, kw_key)
    except Exception:
        return None  # shape inference failed: let the normal path raise
    if seg is None:
        seg = BulkSegment(recording)
        _tls.seg = seg
    if staged is not None:
        for _, x, raw in staged.values():
            seg.ext_index[id(x)] = len(seg.ext_raws)
            seg.ext_raws.append(raw)
            seg.ext_handles.append(x)
    taped = recording and op.differentiable and any_tape
    slots = tuple(slots)
    seg.steps.append((op.partial(kwargs, kw_key), slots, single))
    seg.plan.append((op.name, kw_key, slots, len(avals)))
    outs = []
    for av in avals:
        ref = LazyRef(seg, len(seg.refs), tuple(av.shape), av.dtype, taped)
        nd = _wrap_lazy(wrap, ref)
        seg.refs.append(ref)
        # weak: a dropped intermediate must not be kept alive (and not be
        # materialised) by the segment that produced it
        seg.handles.append(weakref.ref(nd))
        outs.append(nd)
    if len(seg.plan) >= size:
        flush()
    return outs[0] if single else tuple(outs)
