"""AMP core: the trace-level cast hook.

Parity target: `src/nnvm/low_precision_pass.cc` — the reference walks the
nnvm graph inserting `amp_cast`/`amp_multicast` nodes around whitelisted /
blacklisted ops. TPU-native, the same decision runs at trace time: both
dispatch paths (imperative `ndarray._invoke` and the symbolic evaluator
`symbol._build_eval`) call :func:`cast_inputs` on their raw arrays before
invoking the op function, so the casts are traced into the executable and
fused by XLA (a cast feeding an MXU matmul is free).

Kept separate from the `amp` package so the hot dispatch path imports only
this tiny module. `amp.init()` populates the op sets and flips ACTIVE;
GEN is bumped on every (de)activation so executable caches keyed on it
never serve a stale-precision compilation.
"""
from __future__ import annotations

ACTIVE = False
GEN = 0                 # bumped on every state change; part of jit cache keys
TARGET_DTYPE = "bfloat16"
TARGET_OPS = frozenset()
FP32_OPS = frozenset()
WIDEST_OPS = frozenset()

_LOW = ("float16", "bfloat16")


def configure(target_dtype, target_ops, fp32_ops, widest_ops):
    global ACTIVE, GEN, TARGET_DTYPE, TARGET_OPS, FP32_OPS, WIDEST_OPS
    TARGET_DTYPE = target_dtype
    TARGET_OPS = frozenset(target_ops)
    FP32_OPS = frozenset(fp32_ops)
    WIDEST_OPS = frozenset(widest_ops)
    ACTIVE = True
    GEN += 1


def deactivate():
    global ACTIVE, GEN
    ACTIVE = False
    GEN += 1


def cache_stale(obj):
    """True when obj's compiled-executable cache predates the current AMP
    generation; stamps obj with the current generation either way. Every
    holder of a jit cache calls this before lookup so no stale-precision
    executable is ever served."""
    stale = getattr(obj, "_amp_gen", GEN) != GEN
    obj._amp_gen = GEN
    return stale


def cast_inputs(op_name, raws):
    """Apply the AMP cast decision for one op's inputs (list of raw jax
    arrays); returns a new list. Called only when ACTIVE."""
    import jax.numpy as jnp

    def isfloat(r):
        return jnp.issubdtype(r.dtype, jnp.floating)

    if op_name in TARGET_OPS:
        tgt = jnp.dtype(TARGET_DTYPE)
        return [r.astype(tgt)
                if isfloat(r) and r.dtype in (jnp.float32, jnp.float64)
                else r for r in raws]
    if op_name in FP32_OPS:
        return [r.astype(jnp.float32)
                if isfloat(r) and str(r.dtype) in _LOW else r for r in raws]
    if op_name in WIDEST_OPS:
        fdts = {r.dtype for r in raws if isfloat(r)}
        if len(fdts) > 1:
            widest = jnp.result_type(*fdts)
            return [r.astype(widest) if isfloat(r) else r for r in raws]
    return raws
