#!/usr/bin/env python
"""Inference throughput across the model zoo (parity:
example/image-classification/benchmark_score.py).

    python examples/image_classification/benchmark_score.py \
        --models resnet50_v1,mobilenet1_0 --batch-sizes 1,32
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def score(model, batch, iters, ctx, dtype="float32"):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if dtype != "float32":
        net.cast(dtype)
    net.hybridize(static_alloc=True)
    size = 299 if model.startswith("inception") else 224
    x = mx.nd.random.uniform(shape=(batch, 3, size, size), ctx=ctx)
    if dtype != "float32":
        x = x.astype(dtype)
    net(x).wait_to_read()  # compile
    net(x).wait_to_read()  # warm
    t0 = time.perf_counter()
    outs = [net(x) for _ in range(iters)]
    outs[-1].wait_to_read()
    return batch * iters / (time.perf_counter() - t0)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", type=str, default="")
    p.add_argument("--batch-sizes", type=str, default="1,32")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--dtype", type=str, default="float32")
    args = p.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx

    from mxnet_tpu.gluon.model_zoo import vision

    ctx = mx.tpu() if mx.num_tpus() > 0 else mx.cpu()
    models = ([m for m in args.models.split(",") if m] or
              ["alexnet", "resnet18_v1", "resnet50_v1", "mobilenet1_0",
               "vgg16", "squeezenet1_0", "densenet121", "inception_v3"])
    known = set(vision.get_model_names())
    for model in models:
        if model not in known:
            print(f"skip unknown model {model}")
            continue
        for batch in (int(b) for b in args.batch_sizes.split(",")):
            ips = score(model, batch, args.iters, ctx, args.dtype)
            print(f"batch size {batch:3d}, dtype {args.dtype}, "
                  f"model {model}: {ips:.1f} img/sec")


if __name__ == "__main__":
    main()
