#!/usr/bin/env python
"""Train an ImageNet-class model — the flagship fit driver.

Parity target: `example/image-classification/train_imagenet.py` +
`common/fit.py:150-321` — full argparse surface (kvstore, lr-step
schedule, checkpoint-per-epoch, top-k metric) plus the `--benchmark 1`
synthetic mode that measures pure training throughput (img/s via
Speedometer) with a device-resident batch, no input pipeline.

    # real data (ImageRecord):
    python train_imagenet.py --data-train train.rec --data-val val.rec
    # throughput benchmark on one chip:
    python train_imagenet.py --benchmark 1 --network resnet50_v1
"""
import argparse
import os
import sys
import tempfile

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import mxnet_tpu as mx

from common import data, fit


def get_network(name, num_classes, image_shape, dtype="float32"):
    """Model-zoo network as a Symbol with a SoftmaxOutput head."""
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(name, classes=num_classes)
    net.initialize(mx.init.Xavier())
    if dtype != "float32":
        net.cast(dtype)
    x = mx.nd.zeros((1,) + image_shape)
    if dtype != "float32":
        x = x.astype(dtype)
    net(x)
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "net"), 0)
        sym, _, _ = mx.model.load_checkpoint(os.path.join(d, "net"), 0)
    return mx.sym.SoftmaxOutput(sym, mx.sym.var("softmax_label"),
                                name="softmax")


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="train imagenet-class models",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    parser.add_argument("--data-train", type=str,
                        help="training ImageRecord (.rec) file")
    parser.add_argument("--data-val", type=str,
                        help="validation ImageRecord (.rec) file")
    parser.add_argument("--image-shape", type=str, default="3,224,224",
                        help="input shape C,H,W")
    parser.add_argument("--num-classes", type=int, default=1000,
                        help="number of classes")
    parser.add_argument("--benchmark", type=int, default=0,
                        help="1 = measure train throughput on a "
                             "synthetic device-resident batch")
    parser.set_defaults(
        network="resnet50_v1",
        num_epochs=1,
        lr=0.1, lr_factor=0.1, lr_step_epochs="30,60,80",
        batch_size=128, num_examples=1281167,
        disp_batches=10,
    )
    args = parser.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    shape = tuple(int(d) for d in args.image_shape.split(","))
    net = get_network(args.network, args.num_classes, shape, args.dtype)

    if args.benchmark:
        # parity: fit.py --benchmark — synthetic feeder, one epoch,
        # Speedometer prints the img/s the driver records
        args.num_epochs = 1
        epoch_size = max(args.num_examples // args.batch_size, 1)

        def synthetic_loader(a, kv):
            return (data.SyntheticDataIter(
                a.num_classes, (a.batch_size,) + shape, epoch_size,
                a.dtype), None)

        return fit.fit(args, net, synthetic_loader)
    return fit.fit(args, net, data.get_rec_iter)


if __name__ == "__main__":
    main()
