#!/usr/bin/env python
"""Train a ResNet on CIFAR-10 with the Module API over the Gluon zoo.

Parity target: `example/image-classification/train_cifar10.py` — same
argparse surface; the network comes from the model zoo (thumbnail
variant for 32x32 inputs) exported to a Symbol, trained via common/fit.

    python examples/image_classification/train_cifar10.py --network resnet18_v1
"""
import argparse
import os
import sys
import tempfile

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import mxnet_tpu as mx


from common import data, fit


def get_network(name, num_classes=10):
    """Model-zoo network as a Symbol with a SoftmaxOutput head."""
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model(name, classes=num_classes, thumbnail=True) \
        if "resnet" in name else vision.get_model(name,
                                                  classes=num_classes)
    net.initialize(mx.init.Xavier())
    x = mx.nd.zeros((1, 3, 32, 32))
    net(x)
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "net"), 0)
        sym, _, _ = mx.model.load_checkpoint(os.path.join(d, "net"), 0)
    return mx.sym.SoftmaxOutput(sym, mx.sym.var("softmax_label"),
                                name="softmax")


def main():
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    parser.set_defaults(network="resnet18_v1", num_epochs=10, lr=0.01,
                        lr_step_epochs="50,100", batch_size=128,
                        num_examples=4096)
    args = parser.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    net = get_network(args.network)
    fit.fit(args, net, data.get_cifar10_iter)


if __name__ == "__main__":
    main()
