#!/usr/bin/env python
"""Fine-tune a pretrained checkpoint on a new label set.

Parity: example/image-classification/fine-tune.py — load a saved
(symbol, params) checkpoint, truncate at the penultimate layer
(`get_internals`), attach a fresh classifier head, and train with the
backbone initialized from the checkpoint.

Self-contained demo: trains a small CNN on synthetic "task A", saves the
checkpoint, then fine-tunes it on "task B" with a different class count.

    python examples/image_classification/fine_tune.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def get_fine_tune_model(symbol, arg_params, num_classes,
                        layer_name="flatten"):
    """parity: fine-tune.py get_fine_tune_model — truncate + new head."""
    import mxnet_tpu as mx

    all_layers = symbol.get_internals()
    net = all_layers[layer_name + "_output"]
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc_new")
    net = mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                               name="softmax")
    wanted = set(net.list_arguments())
    new_args = {k: v for k, v in arg_params.items() if k in wanted}
    return net, new_args


def base_net(num_classes):
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net, name="flatten")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                                name="softmax")


def synthetic(num, classes, seed):
    rs = np.random.RandomState(seed)
    x = rs.rand(num, 1, 8, 8).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * classes).astype(np.int32) % classes
    return x, y.astype(np.float32)


def fit(symbol, x, y, arg_params=None, num_epoch=4, lr=0.1):
    import mxnet_tpu as mx

    mod = mx.mod.Module(symbol)
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": lr},
            arg_params=arg_params or {}, allow_missing=True,
            initializer=mx.init.Xavier())
    it_eval = mx.io.NDArrayIter(x, y, batch_size=32)
    return mod, mod.score(it_eval, "acc")[0][1]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    args = p.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx

    # phase 1: pretrain on task A (3 classes), save checkpoint
    xa, ya = synthetic(512, 3, seed=0)
    mod_a, acc_a = fit(base_net(3), xa, ya, num_epoch=args.epochs)
    print(f"task A accuracy: {acc_a:.3f}")
    prefix = "/tmp/finetune_demo"
    arg_params, aux_params = mod_a.get_params()
    mx.model.save_checkpoint(prefix, args.epochs, base_net(3),
                             arg_params, aux_params)

    # phase 2: fine-tune on task B (5 classes) from the checkpoint
    symbol, arg_params, _ = mx.model.load_checkpoint(prefix, args.epochs)
    net_b, backbone = get_fine_tune_model(symbol, arg_params,
                                          num_classes=5)
    xb, yb = synthetic(512, 5, seed=1)
    _, acc_b = fit(net_b, xb, yb, arg_params=backbone,
                   num_epoch=args.epochs)
    print(f"task B (fine-tuned) accuracy: {acc_b:.3f}")
    return acc_b


if __name__ == "__main__":
    main()
