#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST with the Module API.

Parity target: `example/image-classification/train_mnist.py` — same
argparse surface and network definitions (mlp :44, lenet via symbols);
runs end-to-end on TPU with `--ctx tpu` (default).

    python examples/image_classification/train_mnist.py --network mlp
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import mxnet_tpu as mx


from common import data, fit


def get_mlp():
    """Multi-layer perceptron (parity: train_mnist.py:44)."""
    d = mx.sym.var("data")
    d = mx.sym.Flatten(d)
    fc1 = mx.sym.FullyConnected(d, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc3, mx.sym.var("softmax_label"),
                                name="softmax")


def get_lenet():
    """LeNet (parity: train_mnist.py get_lenet)."""
    d = mx.sym.var("data")
    conv1 = mx.sym.Convolution(d, kernel=(5, 5), num_filter=20,
                               name="conv1")
    tanh1 = mx.sym.Activation(conv1, act_type="tanh")
    pool1 = mx.sym.Pooling(tanh1, pool_type="max", kernel=(2, 2),
                           stride=(2, 2))
    conv2 = mx.sym.Convolution(pool1, kernel=(5, 5), num_filter=50,
                               name="conv2")
    tanh2 = mx.sym.Activation(conv2, act_type="tanh")
    pool2 = mx.sym.Pooling(tanh2, pool_type="max", kernel=(2, 2),
                           stride=(2, 2))
    flat = mx.sym.Flatten(pool2)
    fc1 = mx.sym.FullyConnected(flat, num_hidden=500, name="fc1")
    tanh3 = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(tanh3, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, mx.sym.var("softmax_label"),
                                name="softmax")


def main():
    parser = argparse.ArgumentParser(
        description="train mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    parser.set_defaults(network="mlp", num_epochs=5, lr=0.01,
                        lr_step_epochs="10", batch_size=64,
                        num_examples=4096)
    args = parser.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    net = get_mlp() if args.network == "mlp" else get_lenet()
    fit.fit(args, net, data.get_mnist_iter)


if __name__ == "__main__":
    main()
