"""Shared training harness for the image-classification examples.

Parity target: `example/image-classification/common/fit.py` (reference
lines: `_get_lr_scheduler` :29, `_load_model` :57, `_save_model` :70,
`add_fit_args` :77, `fit` :150) — argparse surface, lr-step schedule,
checkpoint resume, Speedometer/do_checkpoint callbacks, kvstore wiring,
Module train loop. TPU-native: `--ctx tpu` runs the whole graph as one
XLA executable per batch signature.
"""
from __future__ import annotations

import argparse
import logging
import os
import time

import mxnet_tpu as mx


def _get_lr_scheduler(args, kv):
    """parity: fit.py:29 — factor schedule at --lr-step-epochs."""
    if "lr_factor" not in args or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = args.num_examples // args.batch_size
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",") if l]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr,
                     begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    if steps:
        return (lr, mx.lr_scheduler.MultiFactorScheduler(
            step=steps, factor=args.lr_factor, base_lr=lr))
    return (lr, None)


def _load_model(args, rank=0):
    """parity: fit.py:57."""
    if args.load_epoch is None or not args.model_prefix:
        return (None, None, None)
    model_prefix = args.model_prefix
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix,
                 args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    """parity: fit.py:70."""
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    return mx.callback.do_checkpoint(args.model_prefix)


def add_fit_args(parser):
    """parity: fit.py:77 — the common training argument set."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network")
    train.add_argument("--kv-store", type=str, default="local",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=10,
                       help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1,
                       help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, default="",
                       help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd",
                       help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9,
                       help="momentum for sgd")
    train.add_argument("--wd", type=float, default=1e-4,
                       help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128,
                       help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str,
                       help="model checkpoint prefix")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the "
                            "model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy; 0 means no report")
    train.add_argument("--ctx", type=str, default="tpu",
                       help="device context: tpu or cpu")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameter stats every N batches")
    train.add_argument("--dtype", type=str, default="float32",
                       help="precision: float32 or bfloat16")
    return train


def fit(args, network, data_loader, **kwargs):
    """Train `network` (a Symbol) with the Module API
    (parity: fit.py:150)."""
    kv = mx.kv.create(args.kv_store)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s")
    logging.info("start with arguments %s", args)

    train, val = data_loader(args, kv)

    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        network = sym

    devs = mx.tpu() if args.ctx == "tpu" and mx.num_tpus() > 0 else mx.cpu()
    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.mod.Module(context=devs, symbol=network)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag", "signum", "lbsgd"):
        optimizer_params["momentum"] = args.mom

    monitor = mx.monitor.Monitor(args.monitor, pattern=".*") \
        if args.monitor > 0 else None

    initializer = mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                 magnitude=2)
    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))
    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    checkpoint = _save_model(args, kv.rank)

    model.fit(train,
              begin_epoch=args.load_epoch if args.load_epoch else 0,
              num_epoch=args.num_epochs,
              eval_data=val,
              eval_metric=eval_metrics,
              kvstore=kv,
              optimizer=args.optimizer,
              optimizer_params=optimizer_params,
              initializer=initializer,
              arg_params=arg_params,
              aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint,
              allow_missing=True,
              monitor=monitor,
              **kwargs)
    return model
