"""Data loaders for the examples (parity:
`example/image-classification/common/data.py`).

The reference downloads MNIST/CIFAR from the web; this environment has no
egress, so each loader uses the real dataset when its files are present
(`--data-dir`) and otherwise generates a deterministic synthetic set with
the same shapes/statistics — the training mechanics (iterator protocol,
shape inference, lr schedule, checkpointing) are identical either way.
"""
from __future__ import annotations

import os

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-dir", type=str, default="data/",
                      help="the data directory")
    data.add_argument("--num-examples", type=int, default=4096,
                      help="the number of training examples")
    data.add_argument("--num-val-examples", type=int, default=512,
                      help="the number of validation examples")
    return data


def _synthetic(num, shape, num_classes, sample_seed, center_seed):
    """Class-separable gaussian blobs with image-like statistics
    (pixel std ~0.3 like normalized MNIST/CIFAR, so the example lr
    settings behave as they do on the real data). The class centers come
    from `center_seed` so train and val draw from the SAME distribution
    while their samples differ."""
    centers = 0.3 * np.random.RandomState(center_seed) \
        .randn(num_classes, *shape).astype(np.float32)
    rng = np.random.RandomState(sample_seed)
    y = rng.randint(0, num_classes, num).astype(np.float32)
    x = centers[y.astype(np.int32)] + \
        0.15 * rng.randn(num, *shape).astype(np.float32)
    return x, y


class SyntheticDataIter(mx.io.DataIter):
    """Benchmark feeder (parity: the reference fit.py --benchmark mode's
    SyntheticDataIter): ONE device-resident random batch yielded
    `epoch_size` times, so the measured img/s is pure train-step
    throughput with no host input pipeline in the loop."""

    def __init__(self, num_classes, data_shape, epoch_size, dtype="float32"):
        super().__init__(batch_size=data_shape[0])
        self.batch_size = data_shape[0]
        self.epoch_size = epoch_size
        rs = np.random.RandomState(0)
        x = rs.uniform(-1, 1, data_shape).astype(np.float32)
        y = rs.randint(0, num_classes, data_shape[0]).astype(np.float32)
        self._data = mx.nd.array(x).astype(dtype)
        self._label = mx.nd.array(y)
        self._cur = 0
        self.provide_data = [mx.io.DataDesc("data", data_shape, dtype)]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (data_shape[0],), "float32")]

    def reset(self):
        self._cur = 0

    def next(self):
        if self._cur >= self.epoch_size:
            raise StopIteration
        self._cur += 1
        return mx.io.DataBatch(data=[self._data], label=[self._label],
                               pad=0, provide_data=self.provide_data,
                               provide_label=self.provide_label)


def get_rec_iter(args, kv):
    """ImageRecordIter over --data-train/--data-val .rec files, or the
    synthetic fallback at the same shapes (parity: data.py
    get_rec_iter)."""
    shape = tuple(int(d) for d in args.image_shape.split(","))
    train_rec = getattr(args, "data_train", None)
    if train_rec and os.path.exists(train_rec):
        train = mx.io.ImageRecordIter(
            path_imgrec=train_rec, data_shape=shape,
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True)
        val_rec = getattr(args, "data_val", None)
        val = mx.io.ImageRecordIter(
            path_imgrec=val_rec, data_shape=shape,
            batch_size=args.batch_size) if val_rec and \
            os.path.exists(val_rec) else None
        return train, val
    x, y = _synthetic(args.num_examples, shape, args.num_classes, 11,
                      center_seed=3)
    xv, yv = _synthetic(args.num_val_examples, shape, args.num_classes,
                        12, center_seed=3)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(xv, yv, args.batch_size,
                            label_name="softmax_label")
    return train, val


def get_mnist_iter(args, kv):
    """28x28x1, 10 classes (parity: data.py get_mnist_iter)."""
    shape = (1, 28, 28)
    path = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if os.path.exists(path):
        train = mx.io.MNISTIter(
            image=path,
            label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True)
        val = mx.io.MNISTIter(
            image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size)
        return train, val
    x, y = _synthetic(args.num_examples, shape, 10, 42, center_seed=1)
    xv, yv = _synthetic(args.num_val_examples, shape, 10, 43, center_seed=1)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(xv, yv, args.batch_size)
    return train, val


def get_cifar10_iter(args, kv):
    """32x32x3, 10 classes (parity: data.py get_rec_iter on cifar10)."""
    shape = (3, 32, 32)
    rec = os.path.join(args.data_dir, "cifar10_train.rec")
    if os.path.exists(rec):
        train = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=shape,
            batch_size=args.batch_size, shuffle=True)
        val = mx.io.ImageRecordIter(
            path_imgrec=os.path.join(args.data_dir, "cifar10_val.rec"),
            data_shape=shape, batch_size=args.batch_size)
        return train, val
    x, y = _synthetic(args.num_examples, shape, 10, 7, center_seed=2)
    xv, yv = _synthetic(args.num_val_examples, shape, 10, 8, center_seed=2)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(xv, yv, args.batch_size)
    return train, val
