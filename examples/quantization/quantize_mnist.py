#!/usr/bin/env python
"""Post-training int8 quantization demo.

Parity target: `example/quantization/imagenet_gen_qsym_onedal.py` /
`quantize_model` flow — train fp32, calibrate on a few batches, quantize
to int8, compare accuracy and report the gap. Runs on synthetic
MNIST-like data so it works anywhere; pass --mnist-dir with the idx
files for the real thing.

    python examples/quantization/quantize_mnist.py --ctx tpu
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root
sys.path.insert(0, os.path.join(os.path.dirname(_here),
                                "image_classification"))

import mxnet_tpu as mx


from common import data as common_data  # shared MNIST-or-synthetic iters
from mxnet_tpu.contrib import quantization


def build_sym():
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                                name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mnist-dir", default=None, dest="data_dir")
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--num-val-examples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--calib-batches", type=int, default=5)
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    args = ap.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    args.data_dir = args.data_dir or ""
    train_it, eval_it = common_data.get_mnist_iter(args, None)
    mod = mx.mod.Module(build_sym(), context=ctx)
    mod.fit(train_it, num_epoch=args.num_epochs,
            initializer=mx.init.Xavier(),
            optimizer_params=(("learning_rate", 0.1),
                              ("rescale_grad", 1.0 / args.batch_size)))
    fp32_acc = dict(mod.score(eval_it, "acc"))["accuracy"]
    print(f"fp32 accuracy: {fp32_acc:.4f}")

    arg_params, aux_params = mod.get_params()
    qsym, qarg, qaux = quantization.quantize_model(
        build_sym(), arg_params, aux_params,
        calib_data=train_it,
        num_calib_examples=args.calib_batches * args.batch_size,
        calib_mode="naive")
    qmod = mx.mod.Module(qsym, context=ctx)
    qmod.bind(eval_it.provide_data, eval_it.provide_label,
              for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux, allow_missing=False)
    int8_acc = dict(qmod.score(eval_it, "acc"))["accuracy"]
    print(f"int8 accuracy: {int8_acc:.4f} "
          f"(gap {fp32_acc - int8_acc:+.4f})")
    assert int8_acc > fp32_acc - 0.05, "int8 accuracy dropped > 5%"
    print("done")


if __name__ == "__main__":
    main()
