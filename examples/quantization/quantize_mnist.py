#!/usr/bin/env python
"""Post-training int8 quantization demo.

Parity target: `example/quantization/imagenet_gen_qsym_onedal.py` /
`quantize_model` flow — train fp32, calibrate on a few batches with the
TRUE KL entropy search (`calib_mode="entropy"`, the calibrate.cc
algorithm; `--calib-mode naive|percentile` for A/B), quantize to int8
per output channel, compare accuracy, report the gap — then SERVE the
quantized pair through an `mxnet_tpu.serving` int8 bucket ladder and
show the per-model `weight_dtype` + ladder census. Runs on synthetic
MNIST-like data so it works anywhere; pass --mnist-dir with the idx
files for the real thing.

    python examples/quantization/quantize_mnist.py --ctx tpu
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root
sys.path.insert(0, os.path.join(os.path.dirname(_here),
                                "image_classification"))

import mxnet_tpu as mx


from common import data as common_data  # shared MNIST-or-synthetic iters
from mxnet_tpu.contrib import quantization


def build_sym():
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, mx.sym.var("softmax_label"),
                                name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mnist-dir", default=None, dest="data_dir")
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--num-val-examples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--calib-batches", type=int, default=5)
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["entropy", "naive", "percentile"],
                    help="activation calibration: 'entropy' is the real "
                         "KL threshold search (calibrate.cc parity)")
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--skip-serve", action="store_true",
                    help="skip the served-int8 demo at the end")
    args = ap.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    args.data_dir = args.data_dir or ""
    train_it, eval_it = common_data.get_mnist_iter(args, None)
    mod = mx.mod.Module(build_sym(), context=ctx)
    mod.fit(train_it, num_epoch=args.num_epochs,
            initializer=mx.init.Xavier(),
            optimizer_params=(("learning_rate", 0.1),
                              ("rescale_grad", 1.0 / args.batch_size)))
    fp32_acc = dict(mod.score(eval_it, "acc"))["accuracy"]
    print(f"fp32 accuracy: {fp32_acc:.4f}")

    arg_params, aux_params = mod.get_params()
    qsym, qarg, qaux = quantization.quantize_model(
        build_sym(), arg_params, aux_params,
        calib_data=train_it,
        num_calib_examples=args.calib_batches * args.batch_size,
        calib_mode=args.calib_mode)
    calib = quantization.last_calibration()
    print(f"calibration: mode={calib['mode']} bins={calib['num_bins']} "
          f"over {calib['examples']} examples")
    if args.calib_mode == "entropy":
        for tname, rec in sorted(calib["tensors"].items()):
            print(f"  {tname}: KL threshold {rec['threshold']:.4f} "
                  f"(seen [{rec['min_seen']:.3f}, {rec['max_seen']:.3f}])")
    qmod = mx.mod.Module(qsym, context=ctx)
    qmod.bind(eval_it.provide_data, eval_it.provide_label,
              for_training=False)
    qmod.init_params(arg_params=qarg, aux_params=qaux, allow_missing=False)
    int8_acc = dict(qmod.score(eval_it, "acc"))["accuracy"]
    print(f"int8 accuracy: {int8_acc:.4f} "
          f"(gap {fp32_acc - int8_acc:+.4f})")
    assert int8_acc > fp32_acc - 0.05, "int8 accuracy dropped > 5%"

    if not args.skip_serve:
        serve_int8_demo(qsym, qarg, qaux, eval_it)
    print("done")


def serve_int8_demo(qsym, qarg, qaux, eval_it, requests=32):
    """Serve the quantized pair through its own int8 bucket ladder:
    the loaders auto-detect the int8 weights, the ladder pre-compiles
    at warmup (warming the persistent disk cache when
    MXNET_TPU_CACHE_DIR is set — a warm pod then starts with ZERO
    compiles), and stats() reports weight_dtype per model."""
    import numpy as np

    from mxnet_tpu import compile as compile_service
    from mxnet_tpu import serving

    example_shape = tuple(eval_it.provide_data[0].shape[1:])
    # serve the logits: SoftmaxOutput carries the training label input,
    # which a predict server has no business feeding
    serve_sym = qsym.get_internals()["fc2_output"]
    container = serving.ModelContainer()
    container.add_symbol("mnist_int8", serve_sym, dict(qarg), dict(qaux),
                         example_shape=example_shape, buckets=(2, 4, 8))
    server = serving.ModelServer(container, max_wait_ms=1.0).start()
    server.warmup()
    rng = np.random.RandomState(0)
    for i in range(requests):
        rows = int(rng.randint(1, 9))
        x = rng.rand(rows, *example_shape).astype(np.float32)
        y = server.predict("mnist_int8", x, timeout=30.0)
        assert y.shape[0] == rows
    stats = server.stats()["models"]["mnist_int8"]
    comp = compile_service.stats().get("serving", {})
    print(f"served int8: weight_dtype={stats['weight_dtype']} "
          f"ladder={stats['buckets']} census={stats['bucket_census']} "
          f"p50={stats['p50_ms']}ms")
    print(f"serving compile site: hits={comp.get('hits')} "
          f"misses={comp.get('misses')} "
          f"disk_hits={comp.get('disk_hits')}")
    server.drain(timeout=10.0)


if __name__ == "__main__":
    main()
