#!/usr/bin/env python
"""Matrix factorization with row_sparse embeddings.

Parity target: `example/sparse/matrix_factorization/train.py` in the
reference — user/item latent factors stored as row_sparse weights; each
batch touches only its users'/items' rows, so workers `row_sparse_pull`
just those rows from the kvstore, push row_sparse gradients back, and
the optimizer on the store updates only touched rows (dense
(num_users x factor) traffic never happens).

Synthetic ratings from planted factors stand in for MovieLens
(zero-egress environment); the script asserts the factorization
recovers them (falling RMSE).

    python examples/sparse/matrix_factorization.py --num-epoch 10
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_ratings(num_users, num_items, factor, num_ratings, seed=0):
    rs = np.random.RandomState(seed)
    true_u = rs.randn(num_users, factor).astype(np.float32)
    true_i = rs.randn(num_items, factor).astype(np.float32)
    users = rs.randint(0, num_users, num_ratings)
    items = rs.randint(0, num_items, num_ratings)
    ratings = (true_u[users] * true_i[items]).sum(1).astype(np.float32)
    return users, items, ratings


def main(argv=None):
    p = argparse.ArgumentParser(
        description="sparse matrix factorization",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--num-epoch", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-users", type=int, default=500)
    p.add_argument("--num-items", type=int, default=400)
    p.add_argument("--factor-size", type=int, default=8)
    p.add_argument("--num-ratings", type=int, default=8000)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--kvstore", type=str, default="local")
    args = p.parse_args(argv)

    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx
    from mxnet_tpu.ndarray.sparse import row_sparse_array

    nu, ni, fs = args.num_users, args.num_items, args.factor_size
    users, items, ratings = synthetic_ratings(nu, ni, fs,
                                              args.num_ratings)
    n = len(ratings)
    nbatch = n // args.batch_size

    rs = np.random.RandomState(1)
    kv = mx.kv.create(args.kvstore)
    kv.init("user", mx.nd.array(
        0.5 * rs.randn(nu, fs).astype(np.float32)))
    kv.init("item", mx.nd.array(
        0.5 * rs.randn(ni, fs).astype(np.float32)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=args.lr))

    def pull_rows(key, uniq, dim):
        out = row_sparse_array(
            (np.zeros((len(uniq), fs), np.float32),
             uniq.astype(np.int64)), shape=(dim, fs))
        kv.row_sparse_pull(key, out=out, row_ids=mx.nd.array(uniq))
        return out.data.asnumpy()

    rmse = None
    for epoch in range(args.num_epoch):
        perm = np.random.RandomState(epoch).permutation(n)
        sq = 0.0
        for b in range(nbatch):
            sel = perm[b * args.batch_size:(b + 1) * args.batch_size]
            u, i, y = users[sel], items[sel], ratings[sel]
            uu, uinv = np.unique(u, return_inverse=True)
            ii, iinv = np.unique(i, return_inverse=True)
            # pull ONLY the touched rows of each factor matrix
            U = pull_rows("user", uu, nu)
            V = pull_rows("item", ii, ni)
            pred = (U[uinv] * V[iinv]).sum(1)
            err = pred - y
            sq += float((err ** 2).sum())
            # per-rating step (classic SGD-MF): each touched row
            # accumulates its own ratings' gradients un-normalized
            g = err[:, None]
            gU = np.zeros_like(U)
            np.add.at(gU, uinv, g * V[iinv])
            gV = np.zeros_like(V)
            np.add.at(gV, iinv, g * U[uinv])
            kv.push("user", row_sparse_array(
                (gU, uu.astype(np.int64)), shape=(nu, fs)))
            kv.push("item", row_sparse_array(
                (gV, ii.astype(np.int64)), shape=(ni, fs)))
        rmse = float(np.sqrt(sq / (nbatch * args.batch_size)))
        print(f"Epoch[{epoch}] Train-RMSE={rmse:.6f}")
    return rmse


if __name__ == "__main__":
    final = main()
    assert final < 1.5, f"matrix factorization failed to learn ({final})"
