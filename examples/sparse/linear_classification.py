#!/usr/bin/env python
"""Sparse linear classification with row_sparse gradients.

Parity: example/sparse/linear_classification/ in the reference — a linear
model over high-dimensional sparse features. The weight gradient is
row_sparse (only the rows the batch touches carry values), the optimizer
runs ON the kvstore (update_on_kvstore, sparse SGD touches only those
rows), and workers pull only the rows they need via `row_sparse_pull` —
dense weight traffic never happens.

Synthetic sparse data stands in for the criteo-style dataset (zero-egress
environment); the mechanics are the reference's.

    python examples/sparse/linear_classification.py --num-epoch 5
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_sparse_data(num_samples=2000, num_features=1000, nnz=12,
                          seed=0):
    """Random sparse rows + a planted linear separator."""
    rs = np.random.RandomState(seed)
    true_w = rs.randn(num_features).astype(np.float32)
    rows, vals, labels = [], [], []
    for _ in range(num_samples):
        idx = rs.choice(num_features, nnz, replace=False)
        v = rs.rand(nnz).astype(np.float32)
        rows.append(idx)
        vals.append(v)
        labels.append(1.0 if (true_w[idx] * v).sum() > 0 else 0.0)
    return np.stack(rows), np.stack(vals), np.asarray(labels, np.float32)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--num-epoch", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-features", type=int, default=1000)
    p.add_argument("--lr", type=float, default=4.0)
    p.add_argument("--kvstore", type=str, default="local")
    args = p.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx

    from mxnet_tpu.ndarray.sparse import row_sparse_array

    rows, vals, labels = synthetic_sparse_data(
        num_features=args.num_features)
    n = rows.shape[0]
    nbatch = n // args.batch_size

    kv = mx.kv.create(args.kvstore)
    kv.init("weight", mx.nd.zeros((args.num_features, 1)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=args.lr))

    acc = 0.0
    for epoch in range(args.num_epoch):
        perm = np.random.RandomState(epoch).permutation(n)
        total_loss, correct = 0.0, 0
        for b in range(nbatch):
            sel = perm[b * args.batch_size:(b + 1) * args.batch_size]
            idx, val, y = rows[sel], vals[sel], labels[sel]
            uniq = np.unique(idx)
            # pull ONLY the touched rows (row_sparse_pull parity)
            pulled = row_sparse_array(
                (np.zeros((len(uniq), 1), np.float32), uniq.astype(np.int64)),
                shape=(args.num_features, 1))
            kv.row_sparse_pull("weight", out=pulled,
                               row_ids=mx.nd.array(uniq))
            w = np.zeros((args.num_features,), np.float32)
            w[np.asarray(pulled.indices.asnumpy(), np.int64)] = \
                pulled.data.asnumpy()[:, 0]
            # logistic forward + loss
            logits = (val * w[idx]).sum(axis=1)
            prob = 1.0 / (1.0 + np.exp(-logits))
            total_loss += float(-np.mean(
                y * np.log(prob + 1e-8) +
                (1 - y) * np.log(1 - prob + 1e-8)))
            correct += int(((prob > 0.5) == (y > 0.5)).sum())
            # row_sparse gradient: only touched rows carry values
            gscale = (prob - y) / len(sel)
            gw = np.zeros((args.num_features,), np.float32)
            np.add.at(gw, idx.reshape(-1),
                      (gscale[:, None] * val).reshape(-1))
            grad = row_sparse_array(
                (gw[uniq][:, None], uniq.astype(np.int64)),
                shape=(args.num_features, 1))
            kv.push("weight", grad)  # sparse SGD applies on the store
        acc = correct / (nbatch * args.batch_size)
        print(f"Epoch[{epoch}] Train-accuracy={acc:.6f}")
        print(f"Epoch[{epoch}] Train-logloss={total_loss / nbatch:.6f}")
    return acc


if __name__ == "__main__":
    final = main()
    assert final > 0.8, f"sparse linear model failed to learn ({final})"
