#!/usr/bin/env python
"""Factorization machine on the real row_sparse path.

Parity target: `example/sparse/factorization_machine/train.py` +
`model.py` in the reference — the FM formulation

    y = w0 + sum_i x_i w_i
        + 0.5 * (||sum_i x_i v_i||^2 - sum_i x_i^2 ||v_i||^2)

with row_sparse linear weights `w` (num_features, 1) and factor matrix
`v` (num_features, factor_size), trained through the kvstore sparse
machinery: workers `row_sparse_pull` ONLY the rows the batch touches,
push row_sparse gradients, and the optimizer on the store updates just
those rows. Dense (num_features x factor_size) traffic never happens —
the point of the reference example, preserved here.

LibSVM data via --data-train (mx.io.LibSVMIter, reference data path);
without it a synthetic planted-FM dataset is generated (zero-egress
environment), and the script asserts the model actually learns it.

    python examples/sparse/factorization_machine.py --num-epoch 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_fm_data(num_samples, num_features, factor_size, nnz, seed=0):
    """Sparse rows labeled by a planted FM (linear + true interaction
    structure), so only a model with factor terms separates it well."""
    rs = np.random.RandomState(seed)
    true_w = 0.5 * rs.randn(num_features).astype(np.float32)
    true_v = 0.8 * rs.randn(num_features, factor_size).astype(np.float32)
    rows, vals, labels = [], [], []
    for _ in range(num_samples):
        idx = rs.choice(num_features, nnz, replace=False)
        x = rs.rand(nnz).astype(np.float32)
        lin = float((true_w[idx] * x).sum())
        s = (x[:, None] * true_v[idx]).sum(0)
        inter = 0.5 * float((s * s).sum() -
                            ((x ** 2)[:, None] * true_v[idx] ** 2).sum())
        rows.append(idx)
        vals.append(x)
        labels.append(1.0 if lin + inter > 0 else 0.0)
    return np.stack(rows), np.stack(vals), np.asarray(labels, np.float32)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="factorization machine (row_sparse)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--data-train", type=str, default=None,
                   help="training set in LibSVM format")
    p.add_argument("--num-epoch", type=int, default=15)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--input-size", type=int, default=2000,
                   help="number of sparse features")
    p.add_argument("--factor-size", type=int, default=8,
                   help="latent factor dimension")
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--kvstore", type=str, default="local")
    p.add_argument("--num-examples", type=int, default=2000)
    p.add_argument("--nnz", type=int, default=10)
    args = p.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx
    from mxnet_tpu.ndarray.sparse import row_sparse_array

    nf, fs = args.input_size, args.factor_size

    if args.data_train and os.path.exists(args.data_train):
        it = mx.io.LibSVMIter(data_libsvm=args.data_train,
                              data_shape=(nf,),
                              batch_size=args.batch_size)
        rows, vals, labels = [], [], []
        for batch in it:
            csr = batch.data[0]
            dense = csr.asnumpy() if hasattr(csr, "asnumpy") else csr
            for r, y in zip(np.asarray(dense),
                            batch.label[0].asnumpy()):
                idx = np.nonzero(r)[0][:args.nnz]
                if len(idx) < args.nnz:  # pad to fixed nnz
                    idx = np.pad(idx, (0, args.nnz - len(idx)))
                rows.append(idx)
                vals.append(r[idx].astype(np.float32))
                labels.append(float(y))
        rows, vals = np.stack(rows), np.stack(vals)
        labels = np.asarray(labels, np.float32)
    else:
        rows, vals, labels = synthetic_fm_data(
            args.num_examples, nf, fs, args.nnz)

    n = rows.shape[0]
    nbatch = n // args.batch_size

    rs = np.random.RandomState(1)
    kv = mx.kv.create(args.kvstore)
    # row_sparse-initialized weights live ON the store (reference: the
    # Module pulls w/v by batch row ids, optimizer runs on the kvstore)
    kv.init("w", mx.nd.array(0.01 * rs.randn(nf, 1).astype(np.float32)))
    kv.init("v", mx.nd.array(0.1 * rs.randn(nf, fs).astype(np.float32)))
    kv.init("w0", mx.nd.zeros((1,)))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=args.lr))

    def pull_rows(key, uniq, width):
        out = row_sparse_array(
            (np.zeros((len(uniq), width), np.float32),
             uniq.astype(np.int64)), shape=(nf, width))
        kv.row_sparse_pull(key, out=out, row_ids=mx.nd.array(uniq))
        return out.data.asnumpy()

    acc = 0.0
    for epoch in range(args.num_epoch):
        perm = np.random.RandomState(epoch).permutation(n)
        total_loss, correct = 0.0, 0
        for b in range(nbatch):
            sel = perm[b * args.batch_size:(b + 1) * args.batch_size]
            idx, x, y = rows[sel], vals[sel], labels[sel]
            uniq, inv = np.unique(idx, return_inverse=True)
            inv = inv.reshape(idx.shape)
            # pull ONLY touched rows of w and v
            w_rows = pull_rows("w", uniq, 1)[:, 0]
            v_rows = pull_rows("v", uniq, fs)
            w0 = float(kv.pull_single("w0").asnumpy()[0]) \
                if hasattr(kv, "pull_single") else None
            if w0 is None:
                out0 = mx.nd.zeros((1,))
                kv.pull("w0", out=out0)
                w0 = float(out0.asnumpy()[0])

            wb = w_rows[inv]                    # (B, nnz)
            vb = v_rows[inv]                    # (B, nnz, fs)
            s = (x[:, :, None] * vb).sum(1)     # (B, fs)
            lin = (x * wb).sum(1)
            inter = 0.5 * ((s * s).sum(1) -
                           ((x ** 2)[:, :, None] * vb ** 2).sum((1, 2)))
            logits = w0 + lin + inter
            prob = 1.0 / (1.0 + np.exp(-logits))
            total_loss += float(-np.mean(
                y * np.log(prob + 1e-8) +
                (1 - y) * np.log(1 - prob + 1e-8)))
            correct += int(((prob > 0.5) == (y > 0.5)).sum())

            # FM gradients, accumulated onto the TOUCHED rows only
            g = (prob - y) / len(sel)           # (B,)
            gw = np.zeros((len(uniq),), np.float32)
            np.add.at(gw, inv.reshape(-1), (g[:, None] * x).reshape(-1))
            gv = np.zeros((len(uniq), fs), np.float32)
            gv_rows = (g[:, None, None] *
                       (x[:, :, None] * s[:, None, :] -
                        (x ** 2)[:, :, None] * vb))
            np.add.at(gv, inv.reshape(-1), gv_rows.reshape(-1, fs))
            kv.push("w", row_sparse_array(
                (gw[:, None], uniq.astype(np.int64)), shape=(nf, 1)))
            kv.push("v", row_sparse_array(
                (gv, uniq.astype(np.int64)), shape=(nf, fs)))
            kv.push("w0", mx.nd.array(np.array([g.sum()], np.float32)))
        acc = correct / (nbatch * args.batch_size)
        print(f"Epoch[{epoch}] Train-accuracy={acc:.6f} "
              f"Train-logloss={total_loss / nbatch:.6f}")
    return acc


if __name__ == "__main__":
    final = main()
    assert final > 0.75, f"factorization machine failed to learn ({final})"
