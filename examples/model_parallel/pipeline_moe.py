#!/usr/bin/env python
"""Model parallelism on the device mesh: pipeline (pp) + experts (ep).

Parity target: `example/model-parallel/` — the reference splits a big
model across GPUs by hand with `group2ctx` placement. Here the same
capability is mesh-native: `parallel.pipeline_apply` runs a stack of
identical blocks as ONE GPipe-scheduled SPMD program over the ``pp``
axis, and `parallel.moe_apply` shards a mixture-of-experts layer over
the ``ep`` axis. Both are differentiable end-to-end, so the demo trains
with plain `jax.grad`.

Runs anywhere: with fewer than --stages devices it provisions a virtual
CPU mesh (same trick as tests/conftest.py).

    python examples/model_parallel/pipeline_moe.py --stages 4
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4, help="pp axis size")
    ap.add_argument("--experts", type=int, default=4, help="ep axis size")
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    need = max(args.stages, args.experts)
    pinned_cpu = os.environ.get("MXTPU_PLATFORM") == "cpu"

    import mxnet_tpu  # noqa: F401  (applies the MXTPU_PLATFORM pin)
    import jax

    if pinned_cpu:
        # must happen before the backend spins up
        jax.config.update("jax_num_cpu_devices", need)
    if len(jax.devices()) < need:
        if pinned_cpu:
            raise RuntimeError(
                f"need {need} devices, have {len(jax.devices())}")
        # too few real devices: re-exec onto a virtual CPU mesh
        os.environ["MXTPU_PLATFORM"] = "cpu"
        os.execv(sys.executable, [sys.executable] + sys.argv)

    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.parallel import (DeviceMesh, moe_apply, pipeline_apply,
                                    stack_expert_params, stack_stage_params)

    rs = np.random.RandomState(0)
    d = args.dim

    # --- pipelined trunk: S identical residual-MLP stages over pp -------
    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w1"]) @ p["w2"]

    stages = [{"w1": jnp.asarray(rs.randn(d, d) * 0.3, jnp.float32),
               "w2": jnp.asarray(rs.randn(d, d) * 0.3, jnp.float32)}
              for _ in range(args.stages)]
    pp_mesh = DeviceMesh({"pp": args.stages},
                         devices=jax.devices()[:args.stages])
    trunk = pipeline_apply(stage_fn, pp_mesh,
                           num_microbatches=args.microbatches)

    # --- MoE head over ep ----------------------------------------------
    def expert_fn(p, x):
        return jnp.tanh(x @ p["w"])

    experts = [{"w": jnp.asarray(rs.randn(d, d) * 0.4, jnp.float32)}
               for _ in range(args.experts)]
    router_w = jnp.asarray(rs.randn(d, args.experts) * 0.1, jnp.float32)
    ep_mesh = DeviceMesh({"ep": args.experts},
                         devices=jax.devices()[:args.experts])
    head = moe_apply(expert_fn, ep_mesh)

    # --- synthetic regression task --------------------------------------
    x = jnp.asarray(rs.randn(args.batch, d), jnp.float32)
    w_true = jnp.asarray(rs.randn(d, d) * 0.5, jnp.float32)
    y_true = jnp.tanh(x @ w_true)

    params = {"stages": stack_stage_params(stages),
              "experts": stack_expert_params(experts),
              "router": router_w}

    def loss_fn(params):
        h = trunk(params["stages"], x)
        out, aux = head(params["experts"], params["router"], h)
        return jnp.mean((out - y_true) ** 2) + 0.01 * aux

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    for step in range(args.steps):
        loss, g = grad_fn(params)
        params = jax.tree_util.tree_map(
            lambda p, gg: p - args.lr * gg, params, g)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
