#!/usr/bin/env python
"""PTB word-level language model with BucketingModule.

Parity target: `example/rnn/bucketing/lstm_bucketing.py` — an LSTM LM
trained with `BucketingModule` over variable-length sentence buckets,
reporting Perplexity. Uses the real PTB files when `--data-dir` has
ptb.train.txt; otherwise a deterministic synthetic corpus with Zipfian
unigram statistics, so the bucketing/perplexity machinery runs anywhere.

    python examples/rnn/train_ptb.py --num-epochs 3 --ctx tpu
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import numpy as np

import mxnet_tpu as mx


def tokenize(path, vocab=None):
    sentences = []
    vocab = vocab if vocab is not None else {"<eos>": 0, "<unk>": 1}
    for line in open(path):
        words = line.split() + ["<eos>"]
        ids = []
        for w in words:
            if w not in vocab:
                vocab[w] = len(vocab)
            ids.append(vocab[w])
        sentences.append(ids)
    return sentences, vocab


def synthetic_corpus(num_sentences, vocab_size, seed):
    """Zipf-distributed token sequences with a simple bigram structure so
    the model has something to learn."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, vocab_size)
    probs = 1.0 / ranks
    probs /= probs.sum()
    sentences = []
    for _ in range(num_sentences):
        length = int(rng.randint(5, 35))
        toks = [int(rng.choice(ranks, p=probs))]
        for _ in range(length - 1):
            # bigram: next token correlates with previous (learnable)
            prev = toks[-1]
            toks.append((prev * 7 + int(rng.choice(ranks, p=probs)))
                        % (vocab_size - 1) + 1)
        sentences.append(toks + [0])
    return sentences


class BucketSentenceIter(mx.io.DataIter):
    """Bucketed sentence iterator (parity: rnn/bucket_io.py
    BucketSentenceIter) — pads each sentence to its bucket length and
    yields batches tagged with bucket_key."""

    def __init__(self, sentences, batch_size, buckets, vocab_size):
        super().__init__(batch_size)
        self.buckets = sorted(buckets)
        self.data = {b: [] for b in self.buckets}
        for s in sentences:
            for b in self.buckets:
                if len(s) <= b:
                    self.data[b].append(s + [0] * (b - len(s)))
                    break
        self.vocab_size = vocab_size
        self.default_bucket_key = max(self.buckets)
        # sequences feed as (tokens[:-1] -> tokens[1:]): length key-1
        self.provide_data = [mx.io.DataDesc(
            "data", (batch_size, self.default_bucket_key - 1))]
        self.provide_label = [mx.io.DataDesc(
            "softmax_label", (batch_size, self.default_bucket_key - 1))]
        self.reset()

    def reset(self):
        self._plan = []
        for b in self.buckets:
            arr = np.asarray(self.data[b], np.float32)
            for s in range(0, len(arr) - self.batch_size + 1,
                           self.batch_size):
                self._plan.append((b, arr[s:s + self.batch_size]))
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        bucket, chunk = self._plan[self._cursor]
        self._cursor += 1
        data = mx.nd.array(chunk[:, :-1])
        label = mx.nd.array(chunk[:, 1:])
        batch = mx.io.DataBatch(
            data=[data], label=[label], pad=0, index=None)
        batch.bucket_key = bucket
        batch.provide_data = [mx.io.DataDesc("data", data.shape)]
        batch.provide_label = [mx.io.DataDesc("softmax_label", label.shape)]
        return batch


def sym_gen_factory(vocab_size, num_embed, num_hidden, batch_size):
    def sym_gen(bucket_key):
        seq_len = bucket_key - 1
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=num_embed, name="embed")
        state = mx.sym.var("lstm_init_state", init=mx.init.Zero(),
                           shape=(1, batch_size, num_hidden))
        cell = mx.sym.var("lstm_init_cell", init=mx.init.Zero(),
                          shape=(1, batch_size, num_hidden))
        rnn_out = mx.sym.RNN(mx.sym.transpose(embed, axes=(1, 0, 2)),
                             state=state, state_cell=cell,
                             state_size=num_hidden, num_layers=1,
                             mode="lstm", name="lstm")
        flat = mx.sym.Reshape(rnn_out, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(flat, num_hidden=vocab_size,
                                     name="pred")
        lab_flat = mx.sym.Reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(pred, lab_flat, name="softmax")
        return sm, ("data",), ("softmax_label",)

    return sym_gen


def main():
    parser = argparse.ArgumentParser(description="PTB LSTM LM")
    parser.add_argument("--data-dir", type=str, default="data/")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-embed", type=int, default=64)
    parser.add_argument("--num-hidden", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=500)
    parser.add_argument("--num-sentences", type=int, default=2000)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--ctx", type=str, default="tpu")
    args = parser.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import logging

    logging.basicConfig(level=logging.INFO)

    ptb = os.path.join(args.data_dir, "ptb.train.txt")
    if os.path.exists(ptb):
        sentences, vocab = tokenize(ptb)
        vocab_size = len(vocab)
    else:
        sentences = synthetic_corpus(args.num_sentences, args.vocab_size,
                                     seed=0)
        vocab_size = args.vocab_size

    buckets = [10, 20, 30, 40]
    it = BucketSentenceIter(sentences, args.batch_size, buckets, vocab_size)
    ctx = mx.tpu() if args.ctx == "tpu" and mx.num_tpus() > 0 else mx.cpu()
    model = mx.mod.BucketingModule(
        sym_gen_factory(vocab_size, args.num_embed, args.num_hidden,
                        args.batch_size),
        default_bucket_key=it.default_bucket_key, context=ctx)
    model.fit(it,
              eval_metric=mx.metric.Perplexity(),
              optimizer="adam",
              optimizer_params={"learning_rate": args.lr},
              initializer=mx.init.Xavier(),
              num_epoch=args.num_epochs,
              batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                         20))


if __name__ == "__main__":
    main()
