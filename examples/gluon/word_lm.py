#!/usr/bin/env python
"""Gluon word-level language model (imperative + hybridized).

Parity target: `example/gluon/word_language_model/train.py` — embedding ->
LSTM -> (optionally weight-tied) decoder, truncated-BPTT training with
gradient clipping, perplexity reporting. Data: real text via --data (one
sentence per line) indexed with `mx.contrib.text.Vocabulary`; otherwise
the same deterministic Zipf/bigram synthetic corpus the PTB example uses,
so it runs anywhere.

    python examples/gluon/word_lm.py --num-epochs 3 --ctx tpu
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import numpy as np

import mxnet_tpu as mx


from mxnet_tpu import gluon
from mxnet_tpu.contrib import text
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.HybridBlock):
    """embedding -> LSTM -> dropout -> dense decoder; optional weight
    tying (decoder shares the embedding matrix)."""

    def __init__(self, vocab_size, embed_dim, hidden, layers, dropout=0.2,
                 tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        self.hidden = hidden
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed_dim)
            self.rnn = rnn.LSTM(hidden, num_layers=layers, dropout=dropout,
                                input_size=embed_dim)
            if tie_weights:
                if embed_dim != hidden:
                    raise ValueError("weight tying needs embed_dim == hidden")
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        params=self.encoder.params)
            else:
                self.decoder = nn.Dense(vocab_size, flatten=False)

    def hybrid_forward(self, F, inputs, state):
        emb = self.drop(self.encoder(inputs))          # (T, B, E)
        out, state = self.rnn(emb, state)
        out = self.drop(out)
        return self.decoder(out), state

    def begin_state(self, batch_size, ctx):
        return self.rnn.begin_state(batch_size=batch_size, ctx=ctx)


def batchify(ids, batch_size):
    """Fold the token stream into (num_steps, batch_size) columns."""
    n = len(ids) // batch_size
    ids = np.asarray(ids[: n * batch_size], np.float32)
    return ids.reshape(batch_size, n).T


def corpus_tokens(args):
    if args.data and os.path.isfile(args.data):
        source = open(args.data).read()
        counter = text.utils.count_tokens_from_str(source)
        vocab = text.Vocabulary(counter, most_freq_count=args.vocab_size)
        ids = vocab.to_indices(source.split())
        return ids, len(vocab)
    # synthetic corpus with strong bigram structure: most tokens follow a
    # fixed successor map, the rest are Zipf draws — an LSTM learns this
    # quickly, so falling perplexity demonstrates the training loop
    rng = np.random.RandomState(42)
    ranks = np.arange(1, args.vocab_size)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()
    succ = rng.permutation(args.vocab_size)
    ids = [int(rng.choice(ranks, p=probs))]
    for _ in range(args.corpus_tokens - 1):
        if rng.rand() < 0.8:
            ids.append(int(succ[ids[-1]]))
        else:
            ids.append(int(rng.choice(ranks, p=probs)))
    return ids, args.vocab_size


def detach(state):
    if isinstance(state, (list, tuple)):
        return [detach(s) for s in state]
    return state.detach()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="text file, one "
                    "sentence per line; synthetic corpus if absent")
    ap.add_argument("--vocab-size", type=int, default=200)
    ap.add_argument("--corpus-tokens", type=int, default=20000)
    ap.add_argument("--embed-dim", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--tied", action="store_true")
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    args = ap.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()
    mx.random.seed(1)
    ids, vocab_size = corpus_tokens(args)
    data = batchify(ids, args.batch_size)   # (T_total, B)

    model = RNNModel(vocab_size, args.embed_dim, args.hidden, args.layers,
                     tie_weights=args.tied)
    model.initialize(mx.init.Xavier(), ctx=ctx)
    model.hybridize()
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.num_epochs):
        state = model.begin_state(args.batch_size, ctx)
        total_nll, total_tok = 0.0, 0
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt], ctx=ctx)
            y = mx.nd.array(data[i + 1:i + 1 + args.bptt], ctx=ctx)
            state = detach(state)  # truncated BPTT boundary
            with mx.autograd.record():
                out, state = model(x, state)
                loss = loss_fn(out.reshape((-1, vocab_size)),
                               y.reshape((-1,)))
            loss.backward()
            grads = [p.grad(ctx) for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(
                grads, args.clip * args.bptt * args.batch_size)
            trainer.step(args.bptt * args.batch_size)
            total_nll += float(loss.sum().asscalar())
            total_tok += loss.size
        ppl = float(np.exp(total_nll / total_tok))
        print(f"epoch {epoch}: perplexity {ppl:.2f}")
    print("done")


if __name__ == "__main__":
    main()
