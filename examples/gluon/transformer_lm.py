#!/usr/bin/env python
"""Transformer language model on the modern TPU stack.

Beyond the reference (MXNet 1.x predates transformer LMs): causal
`TransformerEncoderCell` stack (flash-attention backed) trained with
`parallel.ShardedTrainer` — the whole step (forward+loss+backward+adam)
is ONE compiled SPMD executable over a dp mesh, with optional ZeRO-1
state sharding, rematerialization and gradient accumulation.

Runs anywhere (virtual CPU mesh fallback); synthetic bigram corpus as in
word_lm.py, or --data a local text file.

    python examples/gluon/transformer_lm.py --steps 100
"""
import argparse
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None)
    ap.add_argument("--vocab-size", type=int, default=128)
    ap.add_argument("--corpus-tokens", type=int, default=20000)
    ap.add_argument("--units", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--dp", type=int, default=0,
                    help="dp mesh size (0 = all devices)")
    ap.add_argument("--zero", action="store_true")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--accum-steps", type=int, default=1)
    args = ap.parse_args()

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx  # applies the MXTPU_PLATFORM pin
    import numpy as np

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.nn import TransformerEncoderCell
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    mx.random.seed(0)

    # ----- corpus (same learnable bigram structure as word_lm.py) ------
    if args.data and os.path.isfile(args.data):
        from mxnet_tpu.contrib import text

        src = open(args.data).read()
        vocab = text.Vocabulary(text.utils.count_tokens_from_str(src),
                                most_freq_count=args.vocab_size)
        ids = np.asarray(vocab.to_indices(src.split()), np.int32)
        args.vocab_size = len(vocab)
    else:
        rng = np.random.RandomState(42)
        ranks = np.arange(1, args.vocab_size)
        probs = (1.0 / ranks) / (1.0 / ranks).sum()
        succ = rng.permutation(args.vocab_size)
        ids = [int(rng.choice(ranks, p=probs))]
        for _ in range(args.corpus_tokens - 1):
            ids.append(int(succ[ids[-1]]) if rng.rand() < 0.8
                       else int(rng.choice(ranks, p=probs)))
        ids = np.asarray(ids, np.int32)

    # ----- model --------------------------------------------------------
    class TransformerLM(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(args.vocab_size, args.units)
                self.pos = nn.Embedding(args.seq_len, args.units)
                self.body = nn.HybridSequential()
                for _ in range(args.layers):
                    self.body.add(TransformerEncoderCell(
                        args.units, args.hidden, args.heads, causal=True))
                self.head = nn.Dense(args.vocab_size, flatten=False)

        def hybrid_forward(self, F, tokens, positions):
            h = self.embed(tokens) + self.pos(positions)
            return self.head(self.body(h))

    net = TransformerLM()
    net.initialize(mx.init.Xavier())

    # ----- batches: (B, T) token windows + next-token labels -----------
    T, B = args.seq_len, args.batch_size
    n_win = (len(ids) - 1) // T
    windows = ids[: n_win * T].reshape(n_win, T)
    labels = ids[1: n_win * T + 1].reshape(n_win, T)
    # (T,) position ids -> (T, U) embedding, broadcast over any batch
    # size (gradient accumulation feeds microbatches)
    pos_nd = mx.nd.arange(T)

    class LMLoss(gluon.loss.Loss):
        """Softmax CE over the flattened (B*T, V) logits."""

        def __init__(self):
            super().__init__(weight=None, batch_axis=0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, pred, label):
            return self._ce(pred.reshape((-1, args.vocab_size)),
                            label.reshape((-1,)))

    mesh = DeviceMesh({"dp": args.dp} if args.dp else None)
    net(mx.nd.array(windows[:B].astype(np.float32)), pos_nd)  # shapes

    class WithPos(gluon.HybridBlock):
        """Adapter: ShardedTrainer drives fn(x); positions are constant."""

        def __init__(self, inner, **kw):
            super().__init__(**kw)
            self.inner = inner

        def hybrid_forward(self, F, x):
            return self.inner(x, pos_nd)

    trainer = ShardedTrainer(WithPos(net), LMLoss(), "adam",
                             {"learning_rate": args.lr}, mesh=mesh,
                             zero=args.zero, remat=args.remat,
                             accum_steps=args.accum_steps)
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        sel = rng.randint(0, n_win, B)
        x = mx.nd.array(windows[sel].astype(np.float32))
        y = mx.nd.array(labels[sel].astype(np.float32))
        loss = trainer.step(x, y)
        if step % 20 == 0 or step == args.steps - 1:
            ppl = float(np.exp(min(float(loss.asscalar()), 20.0)))
            print(f"step {step}: loss {float(loss.asscalar()):.3f} "
                  f"ppl {ppl:.1f}")
    print("done")


if __name__ == "__main__":
    main()
