#!/usr/bin/env python
"""Gluon-imperative MNIST training (parity: example/gluon/mnist/mnist.py —
the canonical imperative-mode demo; `--hybridize` flips it to compiled
mode with zero model changes).

Uses the real MNIST via mx.io.MNISTIter when the files are present,
else a synthetic drop-in (zero-egress environment).

    python examples/gluon/mnist.py --epochs 3 --hybridize
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def load_data(batch_size):
    import mxnet_tpu as mx

    path = os.environ.get("MNIST_PATH", "data")
    img = os.path.join(path, "train-images-idx3-ubyte")
    if os.path.exists(img):
        train = mx.io.MNISTIter(image=img,
                                label=os.path.join(
                                    path, "train-labels-idx1-ubyte"),
                                batch_size=batch_size, shuffle=True)
        return train, None
    # synthetic stand-in: 4 gaussian blobs as "digits" 0-3
    rs = np.random.RandomState(0)
    n, classes = 2048, 4
    y = rs.randint(0, classes, n)
    x = rs.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    for i in range(n):
        r, c = divmod(int(y[i]), 2)
        x[i, 0, r * 14:(r + 1) * 14, c * 14:(c + 1) * 14] += 0.8
    return mx.io.NDArrayIter(x, y.astype(np.float32),
                             batch_size=batch_size, shuffle=True), classes


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--hybridize", action="store_true")
    args = p.parse_args(argv)

    # downed-tunnel guard (skippable via MXTPU_SKIP_PROBE)
    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    train_data, classes = load_data(args.batch_size)
    net = gluon.nn.Sequential() if not args.hybridize \
        else gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(128, activation="relu"))
        net.add(gluon.nn.Dense(64, activation="relu"))
        net.add(gluon.nn.Dense(classes or 10))
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    acc = 0.0
    for epoch in range(args.epochs):
        train_data.reset()
        metric.reset()
        for batch in train_data:
            data, label = batch.data[0], batch.label[0]
            with mx.autograd.record():
                out = net(data.reshape((data.shape[0], -1)))
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
        name, acc = metric.get()
        print(f"Epoch[{epoch}] Train-{name}={acc:.6f}")
    return acc


if __name__ == "__main__":
    final = main()
    assert final > 0.9, f"failed to learn ({final})"
