#!/usr/bin/env python
"""BERT-class transformer fine-tune over flash attention + ShardedTrainer.

Stands in for the reference's GluonNLP BERT fine-tune config
(BASELINE.json; reference capability surface: the contrib transformer
ops, `src/operator/contrib/transformer.cc`, driven by gluon blocks):

1. "Pretrain" a small transformer encoder on a masked-token objective
   over synthetic sequences and checkpoint the backbone.
2. Load the backbone into a classifier (encoder + pooled Dense head) and
   FINE-TUNE on a sequence-classification task with `ShardedTrainer` —
   the whole step (fwd + loss + bwd + AdamW-style update) is ONE sharded
   XLA executable over a dp mesh, attention runs through the Pallas
   flash kernel path (`gluon.contrib.nn.MultiHeadAttention`), and the
   same script runs unchanged on a multi-host mesh (dist semantics come
   from the mesh, not the script).

    python examples/gluon/transformer_finetune.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def make_task(num_samples, seq_len, vocab, num_classes, seed=0):
    """Synthetic classification: the class is determined by which marker
    token appears in the sequence — attention must find it."""
    rs = np.random.RandomState(seed)
    x = rs.randint(num_classes, vocab, (num_samples, seq_len))
    y = rs.randint(0, num_classes, num_samples)
    pos = rs.randint(0, seq_len, num_samples)
    x[np.arange(num_samples), pos] = y  # marker token = class id
    return x.astype(np.float32), y.astype(np.float32)


def build_encoder(args, mx, nn, contrib_nn):
    enc = nn.HybridSequential(prefix="encoder_")
    with enc.name_scope():
        enc.add(contrib_nn.SparseEmbedding(args.vocab, args.units))
        for _ in range(args.layers):
            enc.add(contrib_nn.TransformerEncoderCell(
                args.units, args.hidden, args.heads))
    return enc


def main(argv=None):
    p = argparse.ArgumentParser(
        description="transformer fine-tune (BERT-class config)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--units", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--num-classes", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--pretrain-steps", type=int, default=30)
    p.add_argument("--finetune-epochs", type=int, default=6)
    p.add_argument("--num-examples", type=int, default=512)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (0 = all devices)")
    p.add_argument("--checkpoint", type=str, default=None,
                   help="backbone checkpoint path (default: tmp)")
    args = p.parse_args(argv)

    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib import nn as contrib_nn
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    mx.random.seed(0)

    # ---------------------------------------------- 1. pretrain backbone
    class MLMModel(nn.HybridBlock):
        """Encoder + tied-size vocab head (masked-token objective)."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.encoder = build_encoder(args, mx, nn, contrib_nn)
                self.head = nn.Dense(args.vocab, flatten=False)

        def hybrid_forward(self, F, tokens):
            return self.head(self.encoder(tokens))

    x_pre, _ = make_task(args.num_examples, args.seq_len, args.vocab,
                         args.num_classes, seed=1)
    mlm = MLMModel()
    mlm.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(mlm.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(3)
    for step in range(args.pretrain_steps):
        sel = rs.randint(0, args.num_examples, args.batch_size)
        tokens = x_pre[sel].copy()
        mask_pos = rs.randint(0, args.seq_len, args.batch_size)
        target = tokens[np.arange(args.batch_size), mask_pos].copy()
        tokens[np.arange(args.batch_size), mask_pos] = 0  # [MASK]=0
        tk, tg = mx.nd.array(tokens), mx.nd.array(target)
        with mx.autograd.record():
            logits = mlm(tk)[np.arange(args.batch_size), mask_pos]
            loss = sce(logits, tg)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 10 == 0:
            print(f"pretrain step {step} "
                  f"mlm-loss={float(loss.mean().asscalar()):.4f}")
    ckpt = args.checkpoint or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "transformer_backbone.params")
    mlm.encoder.save_parameters(ckpt)
    print(f"backbone checkpoint -> {ckpt}")

    # --------------------------------------- 2. fine-tune the classifier
    class Classifier(nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.encoder = build_encoder(args, mx, nn, contrib_nn)
                self.pool = nn.Dense(args.units, activation="tanh",
                                     flatten=False)
                self.out = nn.Dense(args.num_classes)

        def hybrid_forward(self, F, tokens):
            h = self.encoder(tokens)
            # BERT-style pooling over the first position
            first = F.invoke("slice_axis", h, axis=1, begin=0, end=1)
            return self.out(self.pool(F.invoke("Flatten", first)))

    x, y = make_task(args.num_examples, args.seq_len, args.vocab,
                     args.num_classes, seed=5)
    clf = Classifier()
    clf.initialize(mx.init.Xavier())
    clf.encoder.load_parameters(ckpt)  # warm start from pretraining
    clf(mx.nd.array(x[:args.batch_size]))  # materialize shapes

    ndev = args.dp or len(jax.devices())
    mesh = DeviceMesh({"dp": ndev})
    st = ShardedTrainer(clf, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "adam", {"learning_rate": args.lr, "wd": 1e-4},
                        mesh=mesh)
    nbatch = args.num_examples // args.batch_size
    acc = 0.0
    for epoch in range(args.finetune_epochs):
        perm = np.random.RandomState(epoch).permutation(args.num_examples)
        tot = 0.0
        for b in range(nbatch):
            sel = perm[b * args.batch_size:(b + 1) * args.batch_size]
            tot += float(st.step(mx.nd.array(x[sel]),
                                 mx.nd.array(y[sel])).asscalar())
        pred = st.predict(mx.nd.array(x)).asnumpy().argmax(-1)
        acc = float((pred == y).mean())
        print(f"Epoch[{epoch}] finetune-loss={tot / nbatch:.4f} "
              f"accuracy={acc:.4f}")
    return acc


if __name__ == "__main__":
    final = main()
    assert final > 0.9, f"fine-tune failed to learn ({final})"
