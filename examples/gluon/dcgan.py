#!/usr/bin/env python
"""DCGAN on synthetic images (parity: the reference's example/gluon/dcgan
— alternating generator/discriminator training with transposed convs).

The generator upsamples a latent vector through Conv2DTranspose stacks;
the discriminator is a strided-conv classifier; both train with the
adversarial min-max objective under `autograd.record`. Synthetic
gaussian-blob "images" stand in for LSUN/MNIST (zero-egress
environment) — the training mechanics (two optimizers, detached fake
batch for the D step, BCE objective) are the reference's.

    python examples/gluon/dcgan.py --epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def build_nets(nn, ngf=16, ndf=16, nc=1):
    netG = nn.HybridSequential(prefix="gen_")
    with netG.name_scope():
        # latent (B, nz, 1, 1) -> (B, nc, 16, 16)
        netG.add(nn.Conv2DTranspose(ngf * 2, 4, 1, 0, use_bias=False),
                 nn.BatchNorm(), nn.Activation("relu"),
                 nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
                 nn.BatchNorm(), nn.Activation("relu"),
                 nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False),
                 nn.Activation("tanh"))
    netD = nn.HybridSequential(prefix="disc_")
    with netD.name_scope():
        netD.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
                 nn.LeakyReLU(0.2),
                 nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
                 nn.BatchNorm(), nn.LeakyReLU(0.2),
                 nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return netG, netD


def main(argv=None):
    p = argparse.ArgumentParser(
        description="DCGAN",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--nz", type=int, default=16, help="latent dim")
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--num-examples", type=int, default=512)
    args = p.parse_args(argv)

    from mxnet_tpu.base import probe_backend_or_fallback

    probe_backend_or_fallback()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    rs = np.random.RandomState(0)
    # synthetic 16x16 "images": smooth gaussian bumps in [-1, 1]
    yy, xx = np.mgrid[0:16, 0:16] / 15.0
    centers = rs.rand(args.num_examples, 2)
    real = np.tanh(3.0 * np.exp(
        -(((xx[None] - centers[:, 0, None, None]) ** 2 +
           (yy[None] - centers[:, 1, None, None]) ** 2) / 0.05)) - 0.5)
    real = real[:, None].astype(np.float32)

    netG, netD = build_nets(nn)
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    b = args.batch_size
    if args.num_examples < b:
        p.error(f"--num-examples ({args.num_examples}) must be >= "
                f"--batch-size ({b})")
    ones = mx.nd.ones((b,))
    zeros = mx.nd.zeros((b,))
    nbatch = args.num_examples // b
    d_loss = g_loss = 0.0
    for epoch in range(args.epochs):
        perm = rs.permutation(args.num_examples)
        d_tot = g_tot = 0.0
        for i in range(nbatch):
            data = mx.nd.array(real[perm[i * b:(i + 1) * b]])
            noise = mx.nd.random.normal(shape=(b, args.nz, 1, 1))
            # --- D step: real -> 1, detached fake -> 0
            fake = netG(noise)
            with autograd.record():
                out_real = netD(data).reshape((-1,))
                out_fake = netD(fake.detach()).reshape((-1,))
                lossD = bce(out_real, ones) + bce(out_fake, zeros)
            lossD.backward()
            trainerD.step(b)
            # --- G step: fool D on a fresh fake batch
            with autograd.record():
                out = netD(netG(noise)).reshape((-1,))
                lossG = bce(out, ones)
            lossG.backward()
            trainerG.step(b)
            d_tot += float(lossD.mean().asscalar())
            g_tot += float(lossG.mean().asscalar())
        d_loss, g_loss = d_tot / nbatch, g_tot / nbatch
        print(f"Epoch[{epoch}] D-loss={d_loss:.4f} G-loss={g_loss:.4f}")
    samples = netG(mx.nd.random.normal(
        shape=(4, args.nz, 1, 1))).asnumpy()
    assert samples.shape == (4, 1, 16, 16)
    assert np.isfinite(samples).all()
    return d_loss, g_loss


if __name__ == "__main__":
    main()
