// Sample mxtpu extension library (parity target:
// example/extensions/lib_custom_op/relu_lib.cc in the reference, which
// registers a custom relu through include/mxnet/lib_api.h).
//
// Exports two ops through the mxtpu extension ABI documented in
// mxnet_tpu/library.py:
//   my_relu(x)            elementwise max(x, 0)
//   my_gemm(a, b)         naive host matmul (M,K)x(K,N)->(M,N)
//
// Build:  g++ -shared -fPIC -O2 -o librelu_lib.so relu_lib.cc
// Use:    mx.library.load("librelu_lib.so"); mx.nd.my_relu(x)
#include <cstdint>
#include <cstring>

namespace {

int64_t numel(const int64_t *shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

template <typename T>
void relu_kernel(const T *in, T *out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = in[i] > T(0) ? in[i] : T(0);
}

template <typename T>
void gemm_kernel(const T *a, const T *b, T *c, int64_t m, int64_t k,
                 int64_t n) {
  for (int64_t i = 0; i < m; ++i)
    for (int64_t j = 0; j < n; ++j) {
      T acc = T(0);
      for (int64_t p = 0; p < k; ++p) acc += a[i * k + p] * b[p * n + j];
      c[i * n + j] = acc;
    }
}

}  // namespace

extern "C" {

int mxtpu_lib_version(void) { return 1; }

int mxtpu_lib_num_ops(void) { return 2; }

const char *mxtpu_lib_op_name(int idx) {
  switch (idx) {
    case 0: return "my_relu";
    case 1: return "my_gemm";
    default: return "";
  }
}

int mxtpu_lib_op_infer_shape(int idx, int num_in, const int64_t **in_shapes,
                             const int *in_ndims, int64_t *out_shape,
                             int *out_ndim) {
  if (idx == 0) {
    if (num_in != 1) return 1;
    *out_ndim = in_ndims[0];
    for (int i = 0; i < in_ndims[0]; ++i) out_shape[i] = in_shapes[0][i];
    return 0;
  }
  if (idx == 1) {
    if (num_in != 2 || in_ndims[0] != 2 || in_ndims[1] != 2) return 1;
    if (in_shapes[0][1] != in_shapes[1][0]) return 2;
    *out_ndim = 2;
    out_shape[0] = in_shapes[0][0];
    out_shape[1] = in_shapes[1][1];
    return 0;
  }
  return 3;
}

int mxtpu_lib_op_forward(int idx, int num_in, const void **in,
                         const int64_t **in_shapes, const int *in_ndims,
                         int dtype, void *out, const int64_t *out_shape,
                         int out_ndim) {
  if (idx == 0) {
    int64_t n = numel(in_shapes[0], in_ndims[0]);
    switch (dtype) {
      case 0: relu_kernel(static_cast<const float *>(in[0]),
                          static_cast<float *>(out), n); return 0;
      case 1: relu_kernel(static_cast<const double *>(in[0]),
                          static_cast<double *>(out), n); return 0;
      case 2: relu_kernel(static_cast<const int32_t *>(in[0]),
                          static_cast<int32_t *>(out), n); return 0;
      case 3: relu_kernel(static_cast<const int64_t *>(in[0]),
                          static_cast<int64_t *>(out), n); return 0;
      default: return 4;
    }
  }
  if (idx == 1) {
    int64_t m = in_shapes[0][0], k = in_shapes[0][1], n = in_shapes[1][1];
    switch (dtype) {
      case 0: gemm_kernel(static_cast<const float *>(in[0]),
                          static_cast<const float *>(in[1]),
                          static_cast<float *>(out), m, k, n); return 0;
      case 1: gemm_kernel(static_cast<const double *>(in[0]),
                          static_cast<const double *>(in[1]),
                          static_cast<double *>(out), m, k, n); return 0;
      default: return 4;
    }
  }
  return 3;
}

}  // extern "C"
