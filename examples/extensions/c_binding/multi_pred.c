/* Multi-threaded inference through the C ABI (parity:
 * example/multi_threaded_inference in the reference): N host threads,
 * each with its OWN PredictorHandle over the same checkpoint, running
 * forward passes concurrently. Exercises the ABI's thread-safety
 * contract (every entry point is GIL-safe; XLA owns device execution).
 *
 * usage: multi_pred <symbol.json> <params file> <n_threads> <iters>
 * prints MULTI_PRED_OK <checksum> on success (checksum identical across
 * threads: same weights, same input). */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

typedef struct {
  const char *json;
  const char *params;
  long params_size;
  int iters;
  double checksum;
  int rc;
} Job;

static void *worker(void *arg) {
  Job *job = (Job *)arg;
  job->rc = 1;
  const char *keys[1] = {"data"};
  int64_t indptr[2] = {0, 2};
  int64_t dims[2] = {1, 8};
  PredictorHandle pred = NULL;
  if (MXPredCreate(job->json, job->params, (int)job->params_size, 1, 0, 1,
                   keys, indptr, dims, &pred) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return NULL;
  }
  float input[8];
  for (int i = 0; i < 8; ++i) input[i] = 1.0f;
  double sum = 0.0;
  for (int it = 0; it < job->iters; ++it) {
    if (MXPredSetInput(pred, "data", input, sizeof(input)) != 0 ||
        MXPredForward(pred) != 0) {
      fprintf(stderr, "forward: %s\n", MXGetLastError());
      MXPredFree(pred);
      return NULL;
    }
    int ndim = 0;
    const int64_t *shape = NULL;
    if (MXPredGetOutputShape(pred, 0, &ndim, &shape) != 0) {
      fprintf(stderr, "output shape: %s\n", MXGetLastError());
      MXPredFree(pred);
      return NULL;
    }
    int64_t n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    float *out = (float *)malloc(sizeof(float) * n);
    if (MXPredGetOutput(pred, 0, out, sizeof(float) * n) != 0) {
      free(out);
      MXPredFree(pred);
      return NULL;
    }
    for (int64_t i = 0; i < n; ++i) sum += out[i];
    free(out);
  }
  MXPredFree(pred);
  job->checksum = sum;
  job->rc = 0;
  return NULL;
}

int main(int argc, char **argv) {
  if (argc < 5) return 2;
  long json_size = 0, params_size = 0;
  char *json = read_file(argv[1], &json_size);
  char *params = read_file(argv[2], &params_size);
  if (!json || !params) return 2;
  int n_threads = atoi(argv[3]);
  int iters = atoi(argv[4]);
  if (n_threads < 1 || iters < 1) {
    fprintf(stderr, "n_threads and iters must be >= 1\n");
    return 2;
  }

  Job *jobs = (Job *)calloc(n_threads, sizeof(Job));
  pthread_t *tids = (pthread_t *)calloc(n_threads, sizeof(pthread_t));
  for (int i = 0; i < n_threads; ++i) {
    jobs[i].json = json;
    jobs[i].params = params;
    jobs[i].params_size = params_size;
    jobs[i].iters = iters;
    jobs[i].rc = -1; /* worker must prove success */
    if (pthread_create(&tids[i], NULL, worker, &jobs[i]) != 0) {
      fprintf(stderr, "pthread_create failed for thread %d\n", i);
      return 1;
    }
  }
  for (int i = 0; i < n_threads; ++i) pthread_join(tids[i], NULL);
  for (int i = 0; i < n_threads; ++i) {
    if (jobs[i].rc != 0) {
      fprintf(stderr, "thread %d failed\n", i);
      return 1;
    }
    if (i > 0 && jobs[i].checksum != jobs[0].checksum) {
      fprintf(stderr, "thread %d checksum diverged\n", i);
      return 1;
    }
  }
  printf("MULTI_PRED_OK %.6f\n", jobs[0].checksum);
  free(jobs);
  free(tids);
  free(json);
  free(params);
  return 0;
}
