/* Standalone-inference demo over the MXPred C ABI (parity model:
 * the reference's c_predict_api consumers, e.g. the C++ image-
 * classification predictor example).
 *
 * Usage: predict <symbol.json path> <params path> — prints the argmax of
 * a fixed all-ones input. Built and driven by tests/test_c_api.py.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(call)                                              \
  do {                                                           \
    if ((call) != 0) {                                           \
      fprintf(stderr, "FAIL %s: %s\n", #call, MXGetLastError()); \
      return 1;                                                  \
    }                                                            \
  } while (0)

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: predict symbol.json model.params\n");
    return 2;
  }
  long sym_size = 0, param_size = 0;
  char *symbol_json = read_file(argv[1], &sym_size);
  char *params = read_file(argv[2], &param_size);
  if (!symbol_json || !params) {
    fprintf(stderr, "FAIL reading model files\n");
    return 1;
  }

  const char *input_keys[1] = {"data"};
  int64_t indptr[2] = {0, 2};
  int64_t shape_data[2] = {1, 8}; /* batch 1, 8 features */
  PredictorHandle pred = NULL;
  CHECK(MXPredCreate(symbol_json, params, (int)param_size, 1, 0, 1,
                     input_keys, indptr, shape_data, &pred));

  float input[8];
  for (int i = 0; i < 8; ++i) input[i] = 1.0f;
  CHECK(MXPredSetInput(pred, "data", input, sizeof(input)));
  CHECK(MXPredForward(pred));

  int ndim = 0;
  const int64_t *oshape = NULL;
  CHECK(MXPredGetOutputShape(pred, 0, &ndim, &oshape));
  if (ndim != 2 || oshape[0] != 1) {
    fprintf(stderr, "FAIL output shape\n");
    return 1;
  }
  int classes = (int)oshape[1];
  float *out = (float *)malloc(sizeof(float) * classes);
  CHECK(MXPredGetOutput(pred, 0, out, sizeof(float) * classes));
  int best = 0;
  float sum = 0.0f;
  for (int i = 0; i < classes; ++i) {
    sum += out[i];
    if (out[i] > out[best]) best = i;
  }
  printf("argmax=%d sum=%.4f\n", best, sum);
  CHECK(MXPredFree(pred));
  free(out);
  free(params);
  free(symbol_json);
  printf("PREDICT OK\n");
  return 0;
}
