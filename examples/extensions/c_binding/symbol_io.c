/* Exercise the symbol + container-IO + schema surface of libmxtpu
 * (parity: MXSymbolCreateFromJSON/ListArguments, MXNDArraySave/Load,
 * MXSymbolGetAtomicSymbolInfo in the reference c_api.h).
 *
 * usage: symbol_io <symbol.json path> <save path>
 * prints SYMBOL_IO_OK on success. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(x)                                                     \
  if ((x) != 0) {                                                    \
    fprintf(stderr, "FAIL %s: %s\n", #x, MXGetLastError());          \
    return 1;                                                        \
  }

int main(int argc, char **argv) {
  if (argc < 3) return 2;

  /* reflected op schema */
  const char *info = NULL;
  CHECK(MXSymbolGetAtomicSymbolInfo("Convolution", &info));
  if (strstr(info, "num_filter") == NULL) {
    fprintf(stderr, "schema missing num_filter: %s\n", info);
    return 1;
  }

  /* symbol load -> introspect -> json roundtrip */
  SymbolHandle sym = NULL;
  CHECK(MXSymbolCreateFromFile(argv[1], &sym));
  int n_args = 0, n_outs = 0, n_aux = 0;
  const char **args_names = NULL, **out_names = NULL, **aux_names = NULL;
  CHECK(MXSymbolListArguments(sym, &n_args, &args_names));
  CHECK(MXSymbolListOutputs(sym, &n_outs, &out_names));
  CHECK(MXSymbolListAuxiliaryStates(sym, &n_aux, &aux_names));
  const char *json = NULL;
  CHECK(MXSymbolSaveToJSON(sym, &json));
  SymbolHandle sym2 = NULL;
  CHECK(MXSymbolCreateFromJSON(json, &sym2));
  int n_args2 = 0;
  const char **args2 = NULL;
  CHECK(MXSymbolListArguments(sym2, &n_args2, &args2));
  if (n_args2 != n_args) {
    fprintf(stderr, "arg count changed across json roundtrip\n");
    return 1;
  }
  CHECK(MXSymbolFree(sym));
  CHECK(MXSymbolFree(sym2));

  /* ndarray container save/load roundtrip */
  CHECK(MXRandomSeed(7));
  int64_t shape[2] = {2, 3};
  NDArrayHandle a = NULL;
  CHECK(MXNDArrayCreate(shape, 2, MXTPU_DTYPE_FLOAT32, &a));
  float vals[6] = {1, 2, 3, 4, 5, 6};
  CHECK(MXNDArraySyncCopyFromCPU(a, vals, sizeof(vals)));
  const char *keys[1] = {"w"};
  NDArrayHandle save_h[1] = {a};
  CHECK(MXNDArraySave(argv[2], 1, save_h, keys));
  int n_loaded = 0, n_names = 0;
  NDArrayHandle *loaded = NULL;
  const char **names = NULL;
  CHECK(MXNDArrayLoad(argv[2], &n_loaded, &loaded, &n_names, &names));
  if (n_loaded != 1 || strcmp(names[0], "w") != 0) {
    fprintf(stderr, "load mismatch\n");
    return 1;
  }
  float back[6] = {0};
  CHECK(MXNDArraySyncCopyToCPU(loaded[0], back, sizeof(back)));
  for (int i = 0; i < 6; ++i)
    if (back[i] != vals[i]) {
      fprintf(stderr, "value mismatch at %d\n", i);
      return 1;
    }
  CHECK(MXNDArrayFree(loaded[0]));
  CHECK(MXHandleArrayFree(loaded));
  CHECK(MXNDArrayFree(a));
  printf("SYMBOL_IO_OK args=%d outs=%d aux=%d\n", n_args, n_outs, n_aux);
  return 0;
}
