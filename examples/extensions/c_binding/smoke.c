/* Minimal C host driving the framework through libmxtpu — the "other
 * language binding" demo (parity model: the reference's C-ABI consumers,
 * e.g. cpp-package / c_predict_api users).
 *
 * Build (see tests/test_c_api.py for the exact commands):
 *   g++ ... mxtpu_c_api.cc -o libmxtpu.so
 *   gcc smoke.c -I include -L . -lmxtpu -Wl,-rpath,. -o smoke
 * Run with PYTHONPATH pointing at the repo and MXTPU_PLATFORM=cpu.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxtpu/c_api.h"

#define CHECK(call)                                              \
  do {                                                           \
    if ((call) != 0) {                                           \
      fprintf(stderr, "FAIL %s: %s\n", #call, MXGetLastError()); \
      return 1;                                                  \
    }                                                            \
  } while (0)

int main(void) {
  int version = 0;
  CHECK(MXGetVersion(&version));
  printf("version=%d\n", version);

  int64_t shape[2] = {2, 3};
  NDArrayHandle a, b;
  CHECK(MXNDArrayCreate(shape, 2, MXTPU_DTYPE_FLOAT32, &a));
  CHECK(MXNDArrayCreate(shape, 2, MXTPU_DTYPE_FLOAT32, &b));

  float av[6] = {1, 2, 3, 4, 5, 6};
  float bv[6] = {10, 20, 30, 40, 50, 60};
  CHECK(MXNDArraySyncCopyFromCPU(a, av, sizeof(av)));
  CHECK(MXNDArraySyncCopyFromCPU(b, bv, sizeof(bv)));

  /* c = broadcast_add(a, b) */
  NDArrayHandle inputs[2] = {a, b};
  int num_out = 0;
  NDArrayHandle *outputs = NULL;
  CHECK(MXImperativeInvoke("broadcast_add", 2, inputs, &num_out, &outputs, 0,
                           NULL, NULL));
  if (num_out != 1) {
    fprintf(stderr, "FAIL expected 1 output, got %d\n", num_out);
    return 1;
  }
  float cv[6];
  CHECK(MXNDArraySyncCopyToCPU(outputs[0], cv, sizeof(cv)));
  for (int i = 0; i < 6; ++i) {
    if (cv[i] != av[i] + bv[i]) {
      fprintf(stderr, "FAIL add mismatch at %d: %f\n", i, cv[i]);
      return 1;
    }
  }

  /* string hyper-parameter: reshape to (3, 2) */
  const char *keys[1] = {"shape"};
  const char *vals[1] = {"(3, 2)"};
  int num_out2 = 0;
  NDArrayHandle *outputs2 = NULL;
  CHECK(MXImperativeInvoke("reshape", 1, &outputs[0], &num_out2, &outputs2, 1,
                           keys, vals));
  int ndim = 0;
  const int64_t *rshape = NULL;
  CHECK(MXNDArrayGetShape(outputs2[0], &ndim, &rshape));
  if (ndim != 2 || rshape[0] != 3 || rshape[1] != 2) {
    fprintf(stderr, "FAIL reshape shape\n");
    return 1;
  }

  /* split: multiple outputs */
  const char *skeys[2] = {"num_outputs", "axis"};
  const char *svals[2] = {"3", "1"};
  int num_out3 = 0;
  NDArrayHandle *outputs3 = NULL;
  CHECK(MXImperativeInvoke("SliceChannel", 1, &a, &num_out3, &outputs3, 2,
                           skeys, svals));
  if (num_out3 != 3) {
    fprintf(stderr, "FAIL split outputs=%d\n", num_out3);
    return 1;
  }

  /* error path: bogus op must fail and set the error string */
  NDArrayHandle *outputs4 = NULL;
  int num_out4 = 0;
  if (MXImperativeInvoke("definitely_not_an_op", 1, &a, &num_out4, &outputs4,
                         0, NULL, NULL) == 0 ||
      strlen(MXGetLastError()) == 0) {
    fprintf(stderr, "FAIL error path\n");
    return 1;
  }

  /* op registry is visible through the ABI */
  int op_count = 0;
  const char **op_names = NULL;
  CHECK(MXListAllOpNames(&op_count, &op_names));
  printf("ops=%d\n", op_count);

  CHECK(MXNDArrayWaitAll());

  for (int i = 0; i < num_out3; ++i) MXNDArrayFree(outputs3[i]);
  MXHandleArrayFree(outputs3);
  MXNDArrayFree(outputs2[0]);
  MXHandleArrayFree(outputs2);
  MXNDArrayFree(outputs[0]);
  MXHandleArrayFree(outputs);
  MXNDArrayFree(a);
  MXNDArrayFree(b);
  printf("C API OK\n");
  return 0;
}
