#!/usr/bin/env python
"""Multi-host data-parallel training with ShardedTrainer.

The modern counterpart of `cifar10_dist.py`: instead of a dist_sync
kvstore aggregating per-step, the WHOLE training step is one SPMD
executable over a global mesh spanning every host — each worker feeds
its process-local slice of the global batch and XLA's collectives do the
gradient reduction over ICI/DCN (SURVEY §5.8's TPU mapping). Launch with
the cluster launcher, which sets the jax.distributed rendezvous env:

    python tools/launch.py -n 2 python \
        examples/distributed_training/sharded_trainer_dist.py --steps 30

Single-process runs work too (the mesh is then host-local). The --zero /
--remat / --accum-steps memory levers and the multi-host checkpoint
(rank-0 write, everyone loads) all apply unchanged.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-batch", type=int, default=32,
                    help="batch rows fed by THIS worker per step")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--zero", action="store_true")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--accum-steps", type=int, default=1)
    ap.add_argument("--checkpoint", default=None,
                    help="save states here at the end (rank 0 writes)")
    args = ap.parse_args()

    import jax

    import mxnet_tpu as mx  # joins the rendezvous when launched
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import DeviceMesh, ShardedTrainer

    rank = jax.process_index()
    nworkers = jax.process_count()
    mesh = DeviceMesh()  # all global devices on dp
    print(f"[{rank}] {nworkers} worker(s), mesh {mesh.axis_sizes} over "
          f"{mesh.num_devices} device(s)")

    mx.random.seed(0)  # identical init on every worker
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())

    # each worker's OWN slice of the data (disjoint shards by rank)
    rs = np.random.RandomState(100 + rank)
    centers = np.random.RandomState(7).randn(4, 16) * 2
    labels = rs.randint(0, 4, 4096)
    data = (centers[labels] +
            rs.randn(4096, 16) * 0.3).astype(np.float32)

    net(mx.nd.array(data[: args.local_batch]))  # materialize shapes
    trainer = ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9}, mesh=mesh,
        zero=args.zero, remat=args.remat, accum_steps=args.accum_steps)

    if not 0 < args.local_batch <= len(data) // 2:
        raise SystemExit(
            f"--local-batch must be in [1, {len(data) // 2}]")
    for step in range(args.steps):
        lo = (step * args.local_batch) % (len(data) - args.local_batch)
        x = mx.nd.array(data[lo:lo + args.local_batch])
        y = mx.nd.array(labels[lo:lo + args.local_batch]
                        .astype(np.float32))
        loss = trainer.step(x, y)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"[{rank}] step {step}: loss "
                  f"{float(loss.asscalar()):.4f}")

    # multi-host predict returns the GLOBAL batch's output (each worker
    # fed 256 rows -> nworkers*256 predictions, rank-ordered)
    pred = trainer.predict(mx.nd.array(data[:256])).argmax(axis=1).asnumpy()
    local = pred[rank * 256:(rank + 1) * 256] if len(pred) > 256 else pred
    acc = (local == labels[:256]).mean()
    print(f"[{rank}] final local-shard accuracy: {acc:.3f}")
    if args.checkpoint:
        trainer.save_states(args.checkpoint)
        print(f"[{rank}] checkpoint saved to {args.checkpoint}")
    print(f"[{rank}] done")


if __name__ == "__main__":
    main()
