#!/usr/bin/env python
"""Distributed data-parallel training with a dist_sync kvstore.

Parity: example/distributed_training/cifar10_dist.py in the reference —
each worker trains on its shard of the data, gradients aggregate across
workers through the dist_sync store every step. Launch with the cluster
launcher (which sets the jax.distributed rendezvous env):

    python tools/launch.py -n 2 python \
        examples/distributed_training/cifar10_dist.py --epochs 2

Single-process runs work too (degenerate 1-worker group). Synthetic
CIFAR-shaped data replaces the download (zero-egress environment); swap in
mx.io.ImageRecordIter for the real dataset.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def synthetic_cifar(num=512, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.rand(num, 3, 32, 32).astype(np.float32)
    # planted rule so the model has something to learn
    y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.float32) + \
        2 * (x[:, 0].mean(axis=(1, 2)) > 0.5).astype(np.float32)
    return x, y


def build_net(classes=4):
    import mxnet_tpu as mx

    net = mx.gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(mx.gluon.nn.Conv2D(16, 3, padding=1, activation="relu"))
        net.add(mx.gluon.nn.MaxPool2D(2))
        net.add(mx.gluon.nn.Conv2D(32, 3, padding=1, activation="relu"))
        net.add(mx.gluon.nn.MaxPool2D(2))
        net.add(mx.gluon.nn.Flatten())
        net.add(mx.gluon.nn.Dense(64, activation="relu"))
        net.add(mx.gluon.nn.Dense(classes))
    return net


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--kvstore", type=str, default="dist_sync")
    args = p.parse_args(argv)

    import mxnet_tpu as mx

    kv = mx.kv.create(args.kvstore)
    rank, nworker = kv.rank, kv.num_workers
    print(f"worker {rank}/{nworker} starting")

    x, y = synthetic_cifar()
    # shard the dataset across workers (reference: SplitSampler)
    shard = slice(rank, len(x), nworker)
    x, y = x[shard], y[shard]

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": args.lr}, kvstore=kv)
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    nbatch = len(x) // args.batch_size
    acc = 0.0
    for epoch in range(args.epochs):
        correct, total_loss = 0, 0.0
        for b in range(nbatch):
            xb = mx.nd.array(x[b * args.batch_size:(b + 1) * args.batch_size])
            yb = mx.nd.array(y[b * args.batch_size:(b + 1) * args.batch_size])
            with mx.autograd.record():
                out = net(xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(args.batch_size * nworker)
            total_loss += float(loss.mean().asscalar())
            correct += int((out.asnumpy().argmax(1) ==
                            yb.asnumpy()).sum())
        acc = correct / (nbatch * args.batch_size)
        print(f"Epoch[{epoch}] Train-accuracy={acc:.6f}")
        print(f"Epoch[{epoch}] Train-loss={total_loss / nbatch:.6f}")
    return acc


if __name__ == "__main__":
    main()
